"""Crash-safe control plane (serve/sessionlog.py + the Router's
recovery surface): durable session WAL, epoch fencing, restart and
handoff with exactly-once stream resume.

Correctness anchors:
  * WAL replay is torn-tail-tolerant and idempotent: a SIGKILL
    mid-write truncates the journal, it never poisons it; a duplicate
    token append after a crash-between-fsync-and-ack folds to a no-op
    by absolute index;
  * a finished stream replays as a pure journal read — no engine ever
    re-decodes it; a live stream re-enters the durable-session resume
    path pinned to its journaled fingerprint and a reconnecting client
    splices exactly-once, bit-identical to the uninterrupted decode;
  * epochs fence: a newer claim over the shared journal directory
    makes the old epoch's writes counted refusals — a replaced
    primary can never corrupt the successor's recovery source;
  * quarantine strikes/benches and per-(tenant, class) shed streaks
    survive restart (control-state snapshot), so a crash cannot
    launder a strike streak or a Retry-After escalation.

Cost control: WAL/replay/fencing logic runs on plain files and stub
handles; exactly ONE test builds real engines (module-scoped net),
covering restart + handoff in a single fleet sequence.  The
subprocess SIGKILL leg over HTTP lives in `bench.py --router-smoke`
(and its slow twin here)."""

import json
import os
import tempfile
import threading
import time

import numpy as np
import pytest

from singa_tpu.serve import qos
from singa_tpu.serve.router import (EngineUnavailable, LameDuck,
                                    Overloaded, Router, RouterSpec,
                                    UnknownSession)
from singa_tpu.serve.session import SessionManager
from singa_tpu.serve.sessionlog import (ControlStateStore, SessionWal,
                                        WalStats, claim_epoch,
                                        latest_wal_before, read_epoch,
                                        reduce_sessions, replay_wal,
                                        wal_path, walcheck)
from singa_tpu.utils.faults import FaultSchedule, inject

pytestmark = pytest.mark.wal


def _wal(dir_, epoch=1, **kw):
    kw.setdefault("group_tokens", 4)
    kw.setdefault("group_ms", 5.0)
    kw.setdefault("log_fn", lambda s: None)
    return SessionWal(dir_, epoch, **kw)


# -- WAL append / replay ------------------------------------------------------

def test_wal_roundtrip_and_walcheck(tmp_path):
    d = str(tmp_path)
    w = _wal(d, epoch=1)
    w.append_open("s1-1", [5, 6], 8, "interactive", "acme", None, 3,
                  12.5)
    for i, t in enumerate([10, 11, 12]):
        w.append_tok("s1-1", i, t)
    w.append_resume("s1-1", "engine-1", 3)
    w.append_open("s1-2", [7], 4, "batch", "default", None, 3, None)
    w.append_tok("s1-2", 0, 42)
    w.append_close("s1-2", "done")
    w.close()

    header, records, torn = replay_wal(wal_path(d, 1))
    assert not torn
    assert header["epoch"] == 1 and header["ver"] == 1
    red = reduce_sessions(records)
    assert set(red) == {"s1-1", "s1-2"}
    live = red["s1-1"]
    assert live["terminal"] is None
    assert live["prompt"] == [5, 6] and live["emitted"] == [10, 11, 12]
    assert live["step"] == 3 and live["tenant"] == "acme"
    assert live["resumes"] == 1 and live["engine"] == "engine-1"
    closed = red["s1-2"]
    assert closed["terminal"] == "done" and closed["emitted"] == [42]

    chk = walcheck(wal_path(d, 1))
    assert chk["epoch"] == 1 and not chk["torn_tail"]
    assert chk["sessions"] == 2 and chk["live_sessions"] == 1
    assert chk["journaled_tokens"] == 4
    assert chk["live"][0]["sid"] == "s1-1"


def test_wal_coalesces_contiguous_tokens(tmp_path):
    """Consecutive same-sid tokens become ONE journal record — the
    group commit stays compact at streaming rates."""
    d = str(tmp_path)
    w = _wal(d, epoch=1, group_tokens=1000, group_ms=1000.0)
    w.append_open("s", [1], 8, "interactive", "default", None, 1, None)
    for i in range(6):
        w.append_tok("s", i, 100 + i)
    w.close()
    _, records, _ = replay_wal(wal_path(d, 1))
    toks = [r for r in records if r["k"] == "tok"]
    assert len(toks) == 1
    assert toks[0]["i"] == 0 and toks[0]["t"] == [100 + i
                                                  for i in range(6)]


def test_wal_torn_tail_truncates_never_poisons(tmp_path):
    d = str(tmp_path)
    w = _wal(d, epoch=1)
    w.append_open("s", [1], 8, "interactive", "default", None, 1, None)
    w.append_tok("s", 0, 7)
    w.close()
    # a SIGKILL mid-write: half a record at the tail, then (as if a
    # later writer raced) a VALID-looking record after the tear —
    # replay must stop at the tear, trusting only the prefix
    good = {"k": "tok", "sid": "s", "i": 1, "t": [9]}
    import zlib
    line = json.dumps({"c": zlib.crc32(json.dumps(
        good, sort_keys=True,
        separators=(",", ":")).encode()) & 0xFFFFFFFF, "r": good})
    with open(wal_path(d, 1), "ab") as f:
        f.write(b'{"c": 123, "r": {"k": "tok", "sid')   # torn line
        f.write(b"\n" + line.encode() + b"\n")
    _, records, torn = replay_wal(wal_path(d, 1))
    assert torn
    red = reduce_sessions(records)
    assert red["s"]["emitted"] == [7]     # nothing after the tear


def test_reduce_folds_duplicate_appends_and_gaps():
    records = [
        {"k": "open", "sid": "s", "prompt": [1], "max_new": 8,
         "priority": "interactive", "tenant": "default",
         "family": None, "step": 1, "deadline_rem_s": None},
        {"k": "tok", "sid": "s", "i": 0, "t": [10, 11]},
        # duplicate flush after a crash-between-fsync-and-ack:
        # same indices again plus one new token
        {"k": "tok", "sid": "s", "i": 0, "t": [10, 11, 12]},
        # a gap (index 5 with only 3 journaled) keeps the prefix
        {"k": "tok", "sid": "s", "i": 5, "t": [99]},
        # tok for a sid never opened: ignored
        {"k": "tok", "sid": "ghost", "i": 0, "t": [1]},
    ]
    red = reduce_sessions(records)
    assert red["s"]["emitted"] == [10, 11, 12]
    assert "ghost" not in red


def test_epoch_claim_monotonic_and_latest_wal(tmp_path):
    d = str(tmp_path)
    assert read_epoch(d) == 0
    assert claim_epoch(d) == 1
    assert claim_epoch(d) == 2
    assert claim_epoch(d) == 3
    _wal(d, epoch=1).close()
    _wal(d, epoch=2).close()
    # the successor of epoch 3 replays the HIGHEST journal below it
    assert latest_wal_before(d, 3) == wal_path(d, 2)
    assert latest_wal_before(d, 2) == wal_path(d, 1)
    assert latest_wal_before(d, 1) is None


def test_fenced_epoch_refuses_writes(tmp_path):
    d = str(tmp_path)
    stats = WalStats()
    w = _wal(d, epoch=claim_epoch(d), stats=stats)
    w.append_open("s", [1], 8, "interactive", "default", None, 1, None)
    w.flush()
    size_before = os.path.getsize(w.path)
    # a successor claims over us (restart or handoff): the next group
    # commit self-fences instead of writing
    claim_epoch(d)
    w.append_tok("s", 0, 7)
    w.flush()
    assert w.fenced
    assert os.path.getsize(w.path) == size_before
    assert stats.snapshot()["fenced_writes"] >= 1
    # and every append after the fence is a counted refusal
    assert w.append_tok("s", 1, 8) is False
    w.close()


def test_explicit_fence_flushes_pending_first(tmp_path):
    """Handoff ordering: fence() writes what is pending BEFORE
    refusing — the successor's recovery source is complete up to the
    fence."""
    d = str(tmp_path)
    w = _wal(d, epoch=1, group_tokens=1000, group_ms=1000.0)
    w.append_open("s", [1], 8, "interactive", "default", None, 1, None)
    w.append_tok("s", 0, 7)
    w.fence()
    assert w.append_tok("s", 1, 8) is False
    w.close()
    _, records, _ = replay_wal(wal_path(d, 1))
    assert reduce_sessions(records)["s"]["emitted"] == [7]


def test_wal_fault_degrades_to_counted_loss(tmp_path):
    """An injected `router.wal` fault (disk error stand-in) drops the
    batch as counted lost durability — append/flush never raise, the
    stream's tokens never block."""
    d = str(tmp_path)
    stats = WalStats()
    w = _wal(d, epoch=1, stats=stats)
    with inject(FaultSchedule.parse("router.wal@0:error")):
        w.append_open("s", [1], 8, "interactive", "default", None, 1,
                      None)
        w.flush()                        # faulted commit: dropped
        assert stats.snapshot()["wal_lost"] >= 1
        w.append_tok("s", 0, 7)
        w.flush()                        # next commit succeeds
    w.close()
    _, records, _ = replay_wal(wal_path(d, 1))
    red = reduce_sessions(records)
    # the open record was in the dropped batch; the tok survives but
    # has no open to attach to — replay degrades, never corrupts
    assert "s" not in red
    assert stats.snapshot()["wal_appends"] == 2


def test_control_state_store_roundtrip_and_torn(tmp_path):
    d = str(tmp_path)
    store = ControlStateStore(d)
    assert store.load() is None          # missing: clean start
    assert store.save({"epoch": 2, "router": {"members": {}}})
    assert store.load()["epoch"] == 2
    with open(store.path, "w") as f:
        f.write('{"epoch": 2, "rou')     # torn snapshot
    assert store.load() is None          # degrades to clean start


# -- replay-only terminal sessions (no engine re-decode) ---------------------

def test_register_terminal_replays_without_engine():
    mgr = SessionManager()
    rec = {"sid": "s1-9", "prompt": [1, 2], "max_new": 4,
           "priority": "interactive", "tenant": "default",
           "family": None, "step": 3, "emitted": [10, 11, 12],
           "resumes": 0, "terminal": "done"}
    s = mgr.register_terminal(rec)
    assert mgr.get("s1-9") is s and s.attachable
    evs = list(s.attach(resume_from=0))
    toks = [(e["i"], e["token"]) for e in evs if "token" in e]
    assert toks == [(0, 10), (1, 11), (2, 12)]
    done = evs[-1]
    assert done["done"] and done["replayed"]
    assert done["tokens"] == [10, 11, 12] and done["finish"] == "length"
    # reconnect-with-prefix: indices below resume_from are skipped
    evs2 = list(s.attach(resume_from=2))
    assert [(e["i"], e["token"]) for e in evs2
            if "token" in e] == [(2, 12)]


def test_session_manager_bounds_terminal_retention():
    mgr = SessionManager()
    mgr.configure(ttl_s=60.0, cap=3)
    for i in range(6):
        mgr.register_terminal(
            {"sid": f"t{i}", "prompt": [1], "emitted": [i],
             "terminal": "done"})
        mgr._evict()
    snap = mgr.snapshot()
    assert snap["terminal_retained"] <= 3
    assert snap["sessions_evicted"] >= 3
    assert mgr.get("t0") is None and mgr.get("t5") is not None
    # TTL: an expired entry goes on the next sweep
    mgr2 = SessionManager()
    mgr2.configure(ttl_s=0.0, cap=100)
    mgr2.register_terminal({"sid": "x", "prompt": [1], "emitted": [],
                            "terminal": "done"})
    time.sleep(0.01)
    mgr2._evict()
    assert mgr2.get("x") is None
    assert mgr2.stats.snapshot()["sessions_evicted"] == 1


# -- stub-router surface: lame duck, attach errors, state restore ------------

class StubHandle:
    def __init__(self, name, step=1):
        self.name = name
        self.step = step
        self.fail_probe = False

    def probe(self):
        if self.fail_probe:
            raise EngineUnavailable(f"{self.name} is down")
        return {"ok": True, "status": "ok", "step": self.step,
                "queue_depth": 0}

    def stats_snapshot(self):
        return {"completed": 0, "failed": 0, "expired": 0,
                "p95_latency_ms": None}

    def request(self, mode, tokens, timeout=None):
        return {"tokens": [1], "step": self.step}


def _router(n=2, **kw):
    kw.setdefault("quarantine_after", 2)
    kw.setdefault("probe_period_s", 60.0)
    kw.setdefault("readmit_base_s", 30.0)   # benches outlast the test
    stubs = [StubHandle(f"e{i}") for i in range(n)]
    r = Router(stubs, spec=RouterSpec(**kw), log_fn=lambda s: None)
    r.probe_all()
    return r, stubs


def test_lame_duck_refuses_with_successor_hint():
    r, _ = _router(2)
    assert r.route("generate", [1])["step"] == 1
    r.enter_lame_duck(successor="http://next:8000", retry_after=0.25)
    with pytest.raises(LameDuck) as ei:
        r.route("generate", [1])
    assert ei.value.successor == "http://next:8000"
    assert ei.value.retry_after == 0.25
    with pytest.raises(LameDuck):
        r.route_stream([1], max_new=4)
    assert r.stats.lame_duck_refusals == 2
    assert r.snapshot()["lame_duck"] is True


def test_attach_unknown_session_raises_gone():
    r, _ = _router(1)
    with pytest.raises(UnknownSession):
        r.attach_stream("never-journaled")


def test_quarantine_and_shed_streaks_survive_restart():
    """The control-state snapshot closes the restart laundering hole:
    a quarantined engine stays benched for its REMAINING time, and a
    tenant's Retry-After streak keeps escalating where it left off."""
    r1, stubs = _router(2, quarantine_after=2)
    stubs[0].fail_probe = True
    r1.probe_all()
    r1.probe_all()                    # 2 strikes -> quarantined
    assert {m["name"]: m["quarantined"]
            for m in r1.members()}["e0"]
    # build a shed streak for one (tenant, class)
    r1._shed_backoffs.shed_delay("interactive", tenant="acme")
    r1._shed_backoffs.shed_delay("interactive", tenant="acme")
    state = r1.export_control_state()
    assert state["members"]["e0"]["quarantined"]
    assert state["members"]["e0"]["bench_remaining_s"] > 0
    assert state["shed_streaks"] == {"acme\tinteractive": 2}

    # "restart": a fresh router over the same membership
    r2, stubs2 = _router(2, quarantine_after=2)
    assert not any(m["quarantined"] for m in r2.members())
    r2.restore_control_state(state)
    m = {m["name"]: m for m in r2.members()}
    assert m["e0"]["quarantined"] and not m["e1"]["quarantined"]
    assert r2.healthy_names() == ["e1"]
    # the restored bench holds: a probe round does NOT readmit early
    r2.probe_all()
    assert {m["name"]: m["quarantined"]
            for m in r2.members()}["e0"]
    assert r2._shed_backoffs.export_streaks() == {
        "acme\tinteractive": 2}


def test_shed_streak_export_restore_grammar():
    b = qos.ClassBackoffs(seed=0)
    b.shed_delay("batch", tenant="a")
    b.shed_delay("batch", tenant="a")
    b.shed_delay("interactive", tenant="b")
    b.reset("interactive", tenant="b")   # streak resets -> not exported
    out = b.export_streaks()
    assert out == {"a\tbatch": 2}
    b2 = qos.ClassBackoffs(seed=0)
    b2.restore_streaks(out)
    assert b2.export_streaks() == {"a\tbatch": 2}
    # garbage keys degrade to ignored, never raise
    b2.restore_streaks({"no-tab": 3, "x\ty": "bad"})


# -- satellite: supervised reload poll (silent-death fix) --------------------

def test_reload_poll_death_is_counted_and_survived():
    """An unexpected exception in the reload poll used to kill the
    daemon thread silently — stale params behind a healthy /healthz
    forever.  Now each death is counted, the loop restarts after a
    Backoff delay, and health degrades once the streak crosses
    `degraded_after`."""
    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import InferenceEngine, InferenceServer, \
        ServeSpec

    cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=16,
                         num_heads=2, head_dim=8, seq_len=8,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (8,), "target": (8,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    spec = ServeSpec(buckets=((2, 8),), max_new_tokens=2,
                     reload_poll_s=0.01, degraded_after=2)
    eng = InferenceEngine(net, spec, params=params,
                          log_fn=lambda s: None)

    def boom():
        raise RuntimeError("poll exploded")

    eng.poll_reload = boom
    srv = InferenceServer(eng, http=False, warmup_modes=(),
                          log_fn=lambda s: None)
    srv.start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                srv.stats.snapshot()["reload_poll_deaths"] < 2:
            time.sleep(0.01)
        snap = srv.stats.snapshot()
        assert snap["reload_poll_deaths"] >= 2
        assert srv._poll_thread.is_alive()   # supervised, not dead
        h = eng.health()
        assert not h["ok"]
        assert any("reload poll died" in s for s in h["reasons"])
        # recovery clears the degradation
        eng.note_poll_ok()
        assert eng.health()["ok"]
    finally:
        srv.stop()


# -- satellite: HttpEngineHandle connection hygiene (fd-flat) ----------------

def test_http_handle_fds_flat_under_churn():
    """500 churned calls — successes, HTTP errors, and streams closed
    early — must not grow this process's open-fd count: every error
    body and every stream response is closed deterministically, not
    left to GC (PR 15's singa_process_open_fds watches the same
    signal in production)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from singa_tpu.serve.router import HttpEngineHandle

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def _json(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._json(200, {"ok": True, "status": "ok",
                                 "step": 1})
            else:
                self._json(500, {"error": "boom"})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            if req.get("stream"):
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                for i in range(4):
                    line = json.dumps({"token": i, "i": i}).encode() \
                        + b"\n"
                    self.wfile.write(f"{len(line):X}\r\n".encode()
                                     + line + b"\r\n")
                self.wfile.write(
                    b"0\r\n\r\n")
            else:
                self._json(500, {"error": "boom"})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    h = HttpEngineHandle(
        "e0", f"http://127.0.0.1:{httpd.server_address[1]}")

    def nfds():
        return len(os.listdir("/proc/self/fd"))

    try:
        for _ in range(10):              # settle urllib/socket caches
            h.probe()
        base = nfds()
        for k in range(500):
            if k % 3 == 0:
                h.probe()                # 200 + a 500 /stats inside
            elif k % 3 == 1:
                with pytest.raises(EngineUnavailable):
                    h.request("generate", [1, 2])   # 500 error body
            else:
                gen = h.request_stream([1], max_new=4)
                next(gen)
                gen.close()              # client walks away mid-body
        assert nfds() <= base + 8, \
            f"fd leak under churn: {base} -> {nfds()}"
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- the tentpole over real engines: restart + handoff -----------------------

@pytest.fixture(scope="module")
def tiny_lm():
    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm

    seq = 16
    cfg = transformer_lm(vocab_size=64, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    return net, net.init_params(jax.random.PRNGKey(0)), seq


def _make_fleet(tiny_lm, ws, standby=False, log=lambda s: None):
    from singa_tpu.serve import EngineFleet, ServeSpec

    net, params, seq = tiny_lm
    spec = ServeSpec(buckets=((2, seq),), max_new_tokens=8,
                     batch_window_s=0.002, request_timeout_s=60.0,
                     cb="on", cb_slots=3, cb_block_len=4)
    rspec = RouterSpec(probe_period_s=0.1, hedge="off",
                       request_timeout_s=60.0, wal_group_tokens=4,
                       wal_group_ms=5.0, state_snapshot_s=0.1)
    return EngineFleet.local(net, spec, 1, workspace=ws,
                             params=params, router_spec=rspec,
                             standby=standby, log_fn=log)


def test_router_restart_resumes_stream_exactly_once(tiny_lm):
    """The tentpole, in-process: a stream is cut mid-decode by a
    router 'crash' (the fleet object is abandoned, never stopped —
    exactly what SIGKILL leaves behind: a WAL with no close record);
    a successor fleet over the same workspace claims the next epoch,
    replays the journal, re-admits the stream pinned to the journaled
    fingerprint, and the reconnecting client's spliced stream is
    BIT-IDENTICAL to an uninterrupted reference — with the old
    epoch's journal fenced against late writes."""
    import numpy as _np

    from singa_tpu.utils.checkpoint import CheckpointManager

    net, params, seq = tiny_lm
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        mgr.save(1, params, {"t": _np.zeros(())},
                 health={"verdict": "ok"})
        prompt = _np.arange(1, 5, dtype=_np.int32)

        # reference: uninterrupted greedy decode (also proves a
        # finished stream's journal replays as terminal later)
        f0 = _make_fleet(tiny_lm, ws)
        f0.start()
        assert f0.epoch == 1
        ref = [ev["token"]
               for ev in f0.generate_stream(prompt, max_new=8)
               if "token" in ev]
        assert len(ref) == 8
        f0.stop()

        # the victim: consume 3 tokens, then CRASH (abandon, no stop;
        # keep the generator referenced so GC cannot close it and
        # journal a close record a real SIGKILL would never write)
        f1 = _make_fleet(tiny_lm, ws)
        f1.start()
        assert f1.epoch == 2
        stream = f1.generate_stream(prompt, max_new=8)
        seen, sid, epoch_seen = [], None, None
        for ev in stream:
            if sid is None and "sid" in ev:
                sid, epoch_seen = ev["sid"], ev.get("epoch")
            if "token" in ev:
                seen.append(ev["token"])
            if len(seen) >= 3:
                break
        assert sid is not None and epoch_seen == 2
        assert sid.startswith("s2-")   # epoch-namespaced: no collision
        f1.wal.flush()                 # the group commit a crash races

        # the successor: claims epoch 3, replays epoch 2's journal
        f2 = _make_fleet(tiny_lm, ws)
        f2.start()
        assert f2.epoch == 3
        out = list(f2.router.attach_stream(sid,
                                           resume_from=len(seen)))
        toks = [ev["token"] for ev in out if "token" in ev]
        done = [ev for ev in out if ev.get("done")][0]
        assert seen + toks == ref      # exactly-once, bit-identical
        assert done["tokens"] == ref and done["spliced"]
        assert done["finish"] == "length"
        snap = f2.wal_stats.snapshot()
        assert snap["recovered_streams"] == 1
        assert snap["replayed_sessions"] >= 1
        assert f2.router.sessions.stats.snapshot()["attached"] == 1
        # second reconnect: the finished session replays from the
        # retained journal — no engine re-decodes it
        again = list(f2.router.attach_stream(sid, resume_from=0))
        assert [e["token"] for e in again if "token" in e] == ref

        # the fenced predecessor cannot corrupt the successor's
        # journal: its next group commit is a counted refusal
        f1.wal.append_close(sid, "done")
        f1.wal.flush()
        assert f1.wal.fenced
        assert f1.wal_stats.snapshot()["fenced_writes"] >= 1

        # handoff leg: lame-duck f2 toward a standby, promote it
        f3 = _make_fleet(tiny_lm, ws, standby=True)
        f3.start()
        assert f3.standby and f3.epoch == 0 and f3.wal is None
        got = f2.handoff(successor="http://standby:9")
        assert got["lame_duck"] and f2.wal.fenced
        with pytest.raises(LameDuck) as ei:
            f2.generate(prompt)
        assert ei.value.successor == "http://standby:9"
        promoted = f3.promote_standby()
        assert f3.epoch == 4 and not f3.standby
        # f2 had no live streams at handoff; its terminal sessions
        # replay on the promoted standby
        assert promoted["terminal"] >= 1
        assert [e["token"]
                for e in f3.router.attach_stream(sid, resume_from=0)
                if "token" in e] == ref
        # fresh admissions flow on the new primary
        assert f3.generate(prompt)["step"] == 1
        f3.stop()
        f2.stop()
        stream.close()                 # release f1's abandoned leg
        f1.stop()


def test_recovery_fault_degrades_to_serving_without_replay(tiny_lm):
    """An injected `router.recover` fault (corrupt journal stand-in)
    must not stop the successor from serving NEW traffic — recovery
    is an add-on, not a startup gate."""
    import numpy as _np

    from singa_tpu.utils.checkpoint import CheckpointManager

    net, params, seq = tiny_lm
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        mgr.save(1, params, {"t": _np.zeros(())},
                 health={"verdict": "ok"})
        f0 = _make_fleet(tiny_lm, ws)
        f0.start()
        list(f0.generate_stream(_np.arange(1, 5, dtype=_np.int32),
                                max_new=4))
        f0.stop()
        with inject(FaultSchedule.parse("router.recover@0:error")):
            f1 = _make_fleet(tiny_lm, ws)
            f1.start()
        assert f1.wal_stats.snapshot()["recovered_streams"] == 0
        out = f1.generate(_np.arange(1, 5, dtype=_np.int32))
        assert out["step"] == 1
        f1.stop()


# -- the real thing: SIGKILL a fleet-router subprocess, restart it -----------

@pytest.mark.slow
def test_subprocess_sigkill_restart_resumes_over_http(tmp_path):
    """The whole crash story with a REAL process death: a fleet
    router subprocess is SIGKILLed mid-stream (no atexit, no close
    record — the journal tail is whatever the last group commit made
    durable), restarted on the same port over the same workspace, and
    the reconnecting HTTP client (X-Session-Id + resume_from) splices
    to the bit-identical uninterrupted sequence."""
    import signal
    import subprocess
    import sys
    import urllib.request

    import jax

    from singa_tpu.config import load_model_config
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.data import discover_input_shapes
    from singa_tpu.utils.checkpoint import CheckpointManager

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    conf = os.path.join(repo, "examples/transformer/lm_tiny.conf")
    ws = str(tmp_path)
    # a blessed checkpoint so every incarnation serves the SAME
    # fingerprint (greedy decode is bit-deterministic given it)
    model = load_model_config(conf)
    shapes = discover_input_shapes(model, force_synthetic=True)
    trainer = Trainer(model, shapes, log_fn=lambda s: None)
    net = trainer.test_net or trainer.train_net
    params = net.init_params(jax.random.PRNGKey(0))
    CheckpointManager(ws, log_fn=lambda s: None).save(
        1, params, {"t": np.zeros(())}, health={"verdict": "ok"})

    port = 18533
    url = f"http://127.0.0.1:{port}"
    cmd = [sys.executable, "-m", "singa_tpu.main", "serve",
           "-model_conf", conf, "--workspace", ws,
           "--fleet", "1", "--port", str(port),
           "--serve_spec",
           "buckets=2x16,max_new_tokens=8,batch_window_s=0.005,"
           "cb=on,cb_slots=2,cb_block_len=4",
           "--fleet_spec",
           "probe_period_s=0.2,hedge=off,wal_group_tokens=2,"
           "wal_group_ms=5,state_snapshot_s=0.2"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def launch():
        return subprocess.Popen(cmd, cwd=repo, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def wait_healthy(proc, secs=300.0):
        deadline = time.monotonic() + secs
        while True:
            if proc.poll() is not None:
                pytest.fail("router exited before /healthz")
            try:
                with urllib.request.urlopen(url + "/healthz",
                                            timeout=2.0) as r:
                    if r.status == 200:
                        return
            except Exception:
                pass
            if time.monotonic() > deadline:
                pytest.fail("router never became healthy")
            time.sleep(0.25)

    def stream(body):
        req = urllib.request.Request(
            url + "/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=120.0)

    prompt = [3, 5, 7, 11]
    proc = launch()
    try:
        wait_healthy(proc)
        # reference: one uninterrupted stream
        ref = []
        with stream({"tokens": prompt, "stream": True,
                     "max_new": 8}) as r:
            for line in r:
                ev = json.loads(line)
                if "token" in ev:
                    ref.append(ev["token"])
        assert len(ref) == 8

        # the victim stream: read 3 tokens, then SIGKILL the router
        r = stream({"tokens": prompt, "stream": True, "max_new": 8})
        sid, seen = None, []
        for line in r:
            ev = json.loads(line)
            if sid is None and "sid" in ev:
                sid = ev["sid"]
            if "token" in ev:
                seen.append(ev["token"])
            if len(seen) >= 3:
                break
        assert sid
        # let the group commit (2 tokens / 5 ms) reach the disk, then
        # kill -9: no close record, no flush-on-exit
        time.sleep(0.3)
        proc.send_signal(signal.SIGKILL)
        proc.wait(30)
        try:
            r.close()
        except Exception:
            pass

        # restart on the same port over the same workspace
        proc = launch()
        wait_healthy(proc)
        with stream({"stream": True, "session": sid,
                     "resume_from": len(seen)}) as r2:
            got = [json.loads(line) for line in r2]
        toks = [ev["token"] for ev in got if "token" in ev]
        done = [ev for ev in got if ev.get("done")][0]
        assert seen + toks == ref          # exactly-once, bit-identical
        assert done["tokens"] == ref
        assert done.get("finish") == "length"
        # the journal directory holds both epochs' WALs + state
        rdir = os.path.join(ws, "router")
        assert sorted(f for f in os.listdir(rdir)
                      if f.startswith("wal-"))[:2] == \
            ["wal-00000001.ndjson", "wal-00000002.ndjson"]
    finally:
        proc.kill()
        proc.wait(30)
