"""The shipped example configs must load, build, and train end to end —
the examples ARE the integration suite, as in the reference (SURVEY §4).
"""

import glob
import subprocess
import sys

import jax
import numpy as np
import pytest

from singa_tpu.config import load_cluster_config, load_model_config
from singa_tpu.core.trainer import Trainer
from singa_tpu.data import resolve_data_source
from singa_tpu.parallel import mesh_from_cluster

LM_CONF = "examples/transformer/lm.conf"
CLUSTER_CONF = "examples/transformer/cluster.conf"


def test_lm_conf_loads_and_matches_builder_idiom():
    cfg = load_model_config(LM_CONF)
    types = {l.type for l in cfg.neuralnet.layer}
    assert {"kSequenceData", "kEmbed", "kAttention", "kMoE",
            "kFeedForward", "kLMHead", "kRMSNorm"} <= types
    attn = next(l for l in cfg.neuralnet.layer if l.type == "kAttention")
    assert attn.attention_param.seq_parallel == "ring"
    assert cfg.precision == "bfloat16"
    # tied embeddings via share_param, as the builder emits them
    head = next(l for l in cfg.neuralnet.layer if l.type == "kLMHead")
    assert head.share_param == ["embed/embedding"]


def test_cluster_conf_mesh_axes():
    cluster = load_cluster_config(CLUSTER_CONF)
    mesh = mesh_from_cluster(cluster)
    assert dict(mesh.shape) == {"data": 2, "model": 2, "pipe": 1,
                                "seq": 2, "expert": 1}


def test_lm_conf_trains_a_step():
    cfg = load_model_config(LM_CONF)
    # shrink for test speed; keep the layer graph identical
    sd = next(l for l in cfg.neuralnet.layer if l.type == "kSequenceData")
    sd.seqdata_param.batchsize, sd.seqdata_param.seq_len = 4, 64
    cfg.precision = "float32"
    s = sd.seqdata_param.seq_len
    trainer = Trainer(cfg, {"data": {"input": (s,), "target": (s,)}},
                      donate=False, log_fn=lambda _: None)
    params, opt = trainer.init(0)
    train_iter, _ = resolve_data_source(cfg, 4)
    batch = next(train_iter)
    assert batch["data"]["input"].shape == (4, 64)
    p, o, m = trainer.train_step(params, opt, batch, 0, jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss"]))


def test_cli_runs_example_end_to_end():
    out = subprocess.run(
        [sys.executable, "-m", "singa_tpu.main",
         "-model_conf", LM_CONF, "-cluster_conf", CLUSTER_CONF,
         "--synthetic", "--steps", "2"],
        capture_output=True, text=True, timeout=900,
        env={"PYTHONPATH": ".", "PATH": "/usr/bin:/bin",
             "JAX_PLATFORMS": "cpu", "HOME": "/tmp",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert "mesh: " in out.stdout and "training done" in out.stdout


# every shipped model conf — cluster.conf is a ClusterProto, not a model
MODEL_CONFS = sorted(
    c for c in glob.glob("examples/**/*.conf", recursive=True)
    if not c.endswith("cluster.conf"))


def test_conf_glob_finds_the_expected_families():
    fams = {c.split("/")[1] for c in MODEL_CONFS}
    assert {"mnist", "cifar10", "imagenet", "transformer"} <= fams


@pytest.mark.parametrize("conf", MODEL_CONFS)
def test_every_shipped_conf_trains_through_cli(conf):
    """conf + binary is the whole interface (main.cc:34-58): every conf
    we ship must run end to end through the CLI, with input geometry
    discovered from the net (data/discovery.py), not hardcoded.
    --batchsize shrinks compute for CPU CI; the layer graph and the
    discovered shapes are identical to a full run."""
    out = subprocess.run(
        [sys.executable, "-m", "singa_tpu.main", "-model_conf", conf,
         "--synthetic", "--steps", "2", "--batchsize", "8"],
        capture_output=True, text=True, timeout=900,
        env={"PYTHONPATH": ".", "PATH": "/usr/bin:/bin",
             "JAX_PLATFORMS": "cpu", "HOME": "/tmp"})
    assert out.returncode == 0, (conf, out.stderr[-2000:])
    assert "training done" in out.stdout, (conf, out.stdout[-500:])


def test_discovered_shapes_follow_parser_geometry():
    from singa_tpu.data import discover_input_shapes

    cases = {"examples/cifar10/quick.conf": (3, 32, 32),
             "examples/imagenet/alexnet.conf": (3, 256, 256),
             "examples/mnist/conv.conf": (28, 28)}
    for conf, want in cases.items():
        shapes = discover_input_shapes(load_model_config(conf),
                                       force_synthetic=True)
        got = next(iter(shapes.values()))["pixel"]
        assert got == want, (conf, got)


def test_discovery_peeks_a_real_shard(tmp_path):
    """A live source wins over parser inference: the record IS the
    schema (layer.cc:388-392 reads a sample record in Setup)."""
    from singa_tpu.data import (Record, Shard, SingleLabelImageRecord,
                                discover_input_shapes)

    folder = str(tmp_path)
    with Shard(folder, Shard.KCREATE) as sh:
        rec = Record(image=SingleLabelImageRecord(
            shape=[3, 40, 40], label=1, pixel=b"\x00" * (3 * 40 * 40)))
        sh.insert(b"k0", rec.encode())
    cfg = load_model_config("examples/cifar10/quick.conf")
    data = next(l for l in cfg.neuralnet.layer if l.type == "kShardData")
    data.data_param.path = folder
    shapes = discover_input_shapes(cfg)
    assert shapes[data.name]["pixel"] == (3, 40, 40)


def test_shipped_example_confs_match_zoo_and_reference():
    """examples/{mnist,cifar10,imagenet}/*.conf are generated from the
    model zoo (tools/export_examples); they must load back equal to the
    zoo configs, and the mnist pair must describe the same nets as the
    reference's hand-written mlp.conf/conv.conf."""
    from singa_tpu.models import vision
    from singa_tpu.tools.export_examples import EXAMPLES

    for rel, build in EXAMPLES.items():
        assert load_model_config(f"examples/{rel}") == build(), rel

    ours = load_model_config("examples/mnist/conv.conf")
    ref = load_model_config("/root/reference/examples/mnist/conv.conf")
    # data source differs by design (kShardData here vs the reference's
    # phase-excluded kLMDBData pair); the neuron-layer graph must match.
    skip = {"kShardData", "kLMDBData"}
    assert ([(l.name, l.type) for l in ours.neuralnet.layer
             if l.type not in skip]
            == [(l.name, l.type) for l in ref.neuralnet.layer
                if l.type not in skip])
    assert ours.updater.base_learning_rate == ref.updater.base_learning_rate

    mlp_ours = load_model_config("examples/mnist/mlp.conf")
    mlp_ref = load_model_config("/root/reference/examples/mnist/mlp.conf")
    assert ([(l.type,
              l.inner_product_param.num_output if l.inner_product_param
              else None) for l in mlp_ours.neuralnet.layer
             if l.type not in skip]
            == [(l.type,
                 l.inner_product_param.num_output if l.inner_product_param
                 else None) for l in mlp_ref.neuralnet.layer
                if l.type not in skip])
    assert vision.mlp_mnist() == mlp_ours


def test_viz_dot_and_log_plot(tmp_path):
    """tools/viz: net JSON -> dot (script/graph.py role) and training-log
    -> curves (script/draw.py role)."""
    from singa_tpu.config import load_model_config
    from singa_tpu.core import build_net
    from singa_tpu.tools.viz import (json_to_dot, parse_training_log,
                                     plot_training_log)

    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    net = build_net(cfg, "kTrain", {"data": {"pixel": (28, 28),
                                             "label": ()}}, batchsize=2)
    dot = json_to_dot(net.to_json())
    assert dot.startswith("digraph")
    for name in net.topo:
        assert f'"{name}"' in dot
    assert '"conv1" -> "pool1";' in dot

    log = ("step-0: loss : 2.301234, precision : 0.101562\n"
           "junk line\n"
           "step-30 test: loss : 2.100000, precision : 0.301000\n"
           "step-30: loss : 1.900111, precision : 0.401222\n")
    series = parse_training_log(log)
    assert series["train"]["step"] == [0, 30]
    assert series["test"]["precision"] == [0.301]
    out = tmp_path / "curves.png"
    metrics = plot_training_log(log, str(out))
    assert "loss" in metrics and out.exists() and out.stat().st_size > 0
