"""FLOPs accounting + MFU (utils/flops.py).

The analytic counter is the oracle for the XLA cost-analysis path: on
a matmul/conv-dominated net the two must agree to within the share of
elementwise work XLA additionally counts.
"""

import jax
import numpy as np
import pytest

from singa_tpu.config import load_model_config
from singa_tpu.core.net import build_net
from singa_tpu.utils.flops import (compiled_flops, mfu, net_forward_flops,
                                   net_train_flops, peak_flops)

MNIST_SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def _lenet_net(bs=64):
    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    return build_net(cfg, "kTrain", MNIST_SHAPES, batchsize=bs)


def test_analytic_lenet_flops_formula():
    net = _lenet_net(bs=1)
    # conv1: 2*20*24*24*5*5*1 + conv2: 2*50*8*8*5*5*20 + ip1: 2*800*500
    # + ip2: 2*500*10 (per sample, 2*MACs)
    conv1 = 2 * 20 * 24 * 24 * 25
    conv2 = 2 * 50 * 8 * 8 * 25 * 20
    shapes = {s.name: s.shape for s in net.param_specs.values()}
    ip1 = 2 * int(np.prod(shapes["ip1/weight"]))
    ip2 = 2 * int(np.prod(shapes["ip2/weight"]))
    assert net_forward_flops(net) == conv1 + conv2 + ip1 + ip2
    assert net_train_flops(net) == 3 * net_forward_flops(net)


def test_analytic_scales_linearly_with_batch():
    assert net_forward_flops(_lenet_net(8)) * 8 == \
        net_forward_flops(_lenet_net(64))


def test_compiled_flops_close_to_analytic():
    bs = 32
    net = _lenet_net(bs)
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": rng.integers(0, 256, (bs, 28, 28)).astype(np.uint8),
        "label": rng.integers(0, 10, (bs,)).astype(np.int32)}}

    def fwd(p, b):
        loss, _, _ = net.apply(p, b, train=False)
        return loss

    got = compiled_flops(jax.jit(fwd), params, batch)
    if got is None:
        pytest.skip("backend reports no flops")
    analytic = net_forward_flops(net)
    # XLA adds elementwise/softmax flops on top of the matmul/conv core
    assert analytic <= got <= 1.5 * analytic


def test_mfu_and_peak_lookup():
    class FakeDev:
        device_kind = "TPU v5 lite"
    assert peak_flops(FakeDev()) == 197e12
    # 197e12 flops done in 2s on a 197e12-peak chip → 50% MFU
    assert mfu(197e12, 2.0, FakeDev()) == pytest.approx(0.5)

    class Unknown:
        device_kind = "cpu"
    assert peak_flops(Unknown()) is None
    assert mfu(1e9, 1.0, Unknown()) is None
