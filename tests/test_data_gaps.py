"""Parser/data-surface gaps closed in VERDICT r1 item 6: meanfile,
LMDB fail-loud, MnistProto resize/elastic_freq, grad norms in debug."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.config.schema import model_config_from_dict
from singa_tpu.core.net import build_net
from singa_tpu.data.records import Record, SingleLabelImageRecord


def _rgb_cfg(tmp_path, meanfile=""):
    layers = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": 4}},
        {"name": "rgb", "type": "kRGBImage", "srclayers": "data",
         "rgbimage_param": {"scale": 1.0, "meanfile": meanfile}},
        {"name": "label", "type": "kLabel", "srclayers": "data"},
        {"name": "ip", "type": "kInnerProduct", "srclayers": "rgb",
         "inner_product_param": {"num_output": 10},
         "param": [{"name": "weight"}, {"name": "bias"}]},
        {"name": "loss", "type": "kSoftmaxLoss",
         "srclayers": ["ip", "label"]},
    ]
    return model_config_from_dict({
        "name": "rgbtest", "train_steps": 1,
        "updater": {"type": "kSGD", "base_learning_rate": 0.1,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers}})


SHAPES = {"data": {"pixel": (3, 8, 8), "label": ()}}


def _batch(rng):
    return {"data": {
        "pixel": jnp.asarray(rng.integers(0, 256, (4, 3, 8, 8)),
                             jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (4,)))}}


def test_meanfile_is_loaded_and_subtracted(tmp_path):
    """layer.cc:571-643: the configured mean record is subtracted
    per-pixel before crop/scale."""
    mean = np.full((3, 8, 8), 7.0, np.float32)
    mpath = str(tmp_path / "mean.rec")
    rec = Record(image=SingleLabelImageRecord(
        shape=[3, 8, 8], data=[float(x) for x in mean.ravel()]))
    with open(mpath, "wb") as f:
        f.write(rec.encode())

    rng = np.random.default_rng(0)
    batch = _batch(rng)
    net_plain = build_net(_rgb_cfg(tmp_path), "kTrain", SHAPES)
    net_mean = build_net(_rgb_cfg(tmp_path, meanfile=mpath), "kTrain",
                         SHAPES)
    params = net_plain.init_params(jax.random.PRNGKey(0))
    _, _, out_p = net_plain.apply(params, batch, train=False)
    _, _, out_m = net_mean.apply(params, batch, train=False)
    np.testing.assert_allclose(np.asarray(out_p["rgb"]) - 7.0,
                               np.asarray(out_m["rgb"]), rtol=1e-6)


def test_missing_meanfile_fails_loud(tmp_path):
    from singa_tpu.core.layers import LayerError
    with pytest.raises(LayerError, match="meanfile"):
        build_net(_rgb_cfg(tmp_path, meanfile=str(tmp_path / "nope")),
                  "kTrain", SHAPES)


def test_lmdb_with_real_env_fails_loud(tmp_path):
    from singa_tpu.data import resolve_data_source
    lmdb_dir = tmp_path / "lmdb"
    lmdb_dir.mkdir()
    (lmdb_dir / "data.mdb").write_bytes(b"\x00" * 64)
    cfg = model_config_from_dict({
        "name": "m", "train_steps": 1,
        "updater": {"type": "kSGD", "base_learning_rate": 0.1,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kLMDBData",
             "data_param": {"batchsize": 2, "path": str(lmdb_dir)}},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "mnist", "type": "kMnistImage", "srclayers": "data"},
            {"name": "ip", "type": "kInnerProduct", "srclayers": "mnist",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "weight"}, {"name": "bias"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip", "label"]}]}})
    # r2->r3: the refusal became a real read path (data/lmdb_reader.py);
    # a corrupt env must still fail loudly — since r4 already at
    # resolve time, when shape discovery peeks the first record
    from singa_tpu.data.lmdb_reader import LMDBFormatError
    with pytest.raises(LMDBFormatError):
        train_iter, _ = resolve_data_source(cfg, 2)
        next(iter(train_iter))


def _mnist_cfg(**mnist_kw):
    layers = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": 4}},
        {"name": "mnist", "type": "kMnistImage", "srclayers": "data",
         "mnist_param": {"norm_a": 255.0, **mnist_kw}},
        {"name": "label", "type": "kLabel", "srclayers": "data"},
        {"name": "ip", "type": "kInnerProduct", "srclayers": "mnist",
         "inner_product_param": {"num_output": 10},
         "param": [{"name": "weight"}, {"name": "bias"}]},
        {"name": "loss", "type": "kSoftmaxLoss",
         "srclayers": ["ip", "label"]},
    ]
    return model_config_from_dict({
        "name": "mnisttest", "train_steps": 1,
        "updater": {"type": "kSGD", "base_learning_rate": 0.1,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers}})


def test_mnist_resize_rescales_samples():
    cfg = _mnist_cfg(resize=14)
    net = build_net(cfg, "kTrain", {"data": {"pixel": (28, 28),
                                             "label": ()}})
    assert net.shapes["mnist"] == (4, 14, 14)
    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jnp.asarray(rng.integers(0, 256, (4, 28, 28)),
                             jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (4,)))}}
    params = net.init_params(jax.random.PRNGKey(0))
    _, _, outs = net.apply(params, batch, train=False)
    assert outs["mnist"].shape == (4, 14, 14)


def test_elastic_freq_gates_distortion_by_step():
    """With elastic_freq=4, distortion applies at steps 0,4,8,... and
    the parser is identity(+normalize) on other steps."""
    cfg = _mnist_cfg(alpha=8.0, sigma=6.0, kernel=5, elastic_freq=4)
    shapes = {"data": {"pixel": (28, 28), "label": ()}}
    net = build_net(cfg, "kTrain", shapes)
    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jnp.asarray(rng.integers(0, 256, (4, 28, 28)),
                             jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (4,)))}}
    params = net.init_params(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(3)
    plain = np.asarray(batch["data"]["pixel"]) / 255.0

    _, _, on = net.apply(params, batch, rng=key, train=True, step=4)
    _, _, off = net.apply(params, batch, rng=key, train=True, step=5)
    assert np.max(np.abs(np.asarray(on["mnist"]) - plain)) > 1e-3
    np.testing.assert_allclose(np.asarray(off["mnist"]), plain,
                               rtol=1e-5, atol=1e-6)


def test_debug_info_includes_grad_norms():
    cfg = _mnist_cfg()
    shapes = {"data": {"pixel": (28, 28), "label": ()}}
    net = build_net(cfg, "kTrain", shapes)
    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jnp.asarray(rng.integers(0, 256, (4, 28, 28)),
                             jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (4,)))}}
    params = net.init_params(jax.random.PRNGKey(0))

    def loss_fn(p):
        loss, _, outs = net.apply(p, batch, train=True)
        return loss, outs

    (_, outs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    text = net.debug_info(params, outs, grads)
    assert "grad" in text and "param" in text and "data" in text
    assert "ip/weight" in text
