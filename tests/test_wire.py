"""Zero-copy binary transport (serve/wire.py): frame-codec roundtrip
and fuzz hardening, TokenRing semantics, live binary server + handle
bit-identity against HTTP, transport negotiation with automatic HTTP
fallback, mixed binary+HTTP fleets with cross-boundary failover, the
`wire.frame` fault site, and the HttpEngineHandle keep-alive
regression.  Select with `-m wire`."""

import json
import socket
import tempfile
import threading
import time

import numpy as np
import pytest

from singa_tpu.serve import wire
from singa_tpu.serve.wire import (
    BinaryEngineHandle, BinaryTransportServer, FrameReader,
    LineCoalescer, NegotiatingEngineHandle, TokenRing, WireError,
    WireStats, K_DONE, K_ERR, K_HELLO, K_REQ,
    K_RESULT, K_TOKENS, K_CANCEL, MAGIC, VERSION, OP_GENERATE,
    OP_STREAM, decode_error, decode_qos_header, decode_request,
    decode_tokens, encode_error, encode_qos_header, encode_request,
    frame_parts, send_frame, token_frame_parts)

pytestmark = pytest.mark.wire


# -- codec roundtrip (property-style, every frame kind) ----------------------

def _loop_frame(kind, req_id, header=b"", payload_parts=(),
                stats=None):
    """Encode a frame through a real socketpair and decode it back."""
    a, b = socket.socketpair()
    try:
        st = stats or WireStats()
        send_frame(a, threading.Lock(), kind, req_id, header,
                   payload_parts, stats=st)
        a.close()
        return FrameReader(b, stats=st).read_frame()
    finally:
        b.close()


def test_qos_header_roundtrip_all_fields():
    deadline = time.monotonic() + 12.0
    h = encode_qos_header(deadline=deadline, priority="batch",
                          tenant="acme", trace=("tr-77", 12345),
                          sid="s3-9", resume_from=41)
    d = decode_qos_header(h)
    assert d["priority"] == "batch"
    assert d["tenant"] == "acme"
    assert d["trace"] == ("tr-77", 12345)
    assert d["sid"] == "s3-9"
    assert d["resume_from"] == 41
    # remaining-ms re-anchoring: same clock here, so within ~1s
    assert abs(d["deadline"] - deadline) < 1.0


def test_qos_header_roundtrip_empty():
    d = decode_qos_header(encode_qos_header())
    assert d["deadline"] is None and d["priority"] is None
    assert d["trace"] is None and d["sid"] is None
    assert d["resume_from"] == 0
    assert d["tenant"] == "default"      # check_tenant folds None


def test_request_payload_roundtrip():
    rng = np.random.default_rng(7)
    for _ in range(50):
        toks = rng.integers(0, 1 << 30,
                            int(rng.integers(0, 64))).astype(np.int32)
        p = encode_request(OP_STREAM, toks,
                           timeout=float(rng.random() * 10),
                           max_new=int(rng.integers(1, 100)))
        d = decode_request(p)
        assert d["mode"] == "stream"
        np.testing.assert_array_equal(d["tokens"], toks)
    d = decode_request(encode_request(OP_GENERATE, None))
    assert d["timeout"] is None and d["max_new"] is None
    assert d["step"] is None and d["tokens"].size == 0


def test_every_frame_kind_roundtrips_over_a_socket():
    rng = np.random.default_rng(13)
    cases = [
        (K_HELLO, b"", []),
        (K_REQ, encode_qos_header(priority="interactive", sid="s1-1"),
         [encode_request(OP_GENERATE, [1, 2, 3], timeout=2.0)]),
        (K_RESULT, b"", [json.dumps({"tokens": [4, 5]}).encode()]),
        (K_TOKENS, b"",
         token_frame_parts(9,
                           rng.integers(0, 99, 17).astype(np.int32))),
        (K_DONE, b"", [json.dumps({"done": True}).encode()]),
        (K_ERR, b"", [encode_error(wire.E_OVERLOADED, "busy", 0.5)]),
        (K_CANCEL, b"", []),
    ]
    for kind, header, parts in cases:
        got = _loop_frame(kind, 42, header, parts)
        assert got is not None
        gk, _flags, req_id, ghdr, gpayload = got
        assert gk == kind and req_id == 42
        assert ghdr == bytes(header)
        assert gpayload == b"".join(bytes(p) for p in parts)
    # the TOKENS payload decodes back to the identical int32 array
    first_i, arr = decode_tokens(
        b"".join(bytes(p) for p in
                 token_frame_parts(3, np.arange(8, dtype=np.int32))))
    assert first_i == 3
    np.testing.assert_array_equal(arr, np.arange(8, dtype=np.int32))


def test_error_payload_roundtrip():
    code, ra, msg = decode_error(
        encode_error(wire.E_DEADLINE, "too late", 2.25))
    assert code == wire.E_DEADLINE and ra == 2.25 and msg == "too late"


# -- fuzz hardening: malformed input is a counted close, never a hang --------

def _read_with_stats(raw: bytes):
    """Feed raw bytes to a FrameReader over a socketpair; return
    (result_or_exception, stats)."""
    a, b = socket.socketpair()
    st = WireStats()
    try:
        a.sendall(raw)
        a.close()
        b.settimeout(5.0)               # a hang fails the test, fast
        r = FrameReader(b, stats=st)
        try:
            return r.read_frame(), st
        except WireError as e:
            return e, st
    finally:
        b.close()


def test_garbage_magic_is_counted_malformed():
    out, st = _read_with_stats(b"XX" + b"\x00" * 14)
    assert isinstance(out, WireError)
    assert st.snapshot()["malformed"] == 1


def test_version_skew_is_counted_malformed():
    pre = wire._PREAMBLE.pack(MAGIC, VERSION + 1, K_HELLO, 0, 0, 1,
                              0, 0)
    out, st = _read_with_stats(pre)
    assert isinstance(out, WireError) and "version skew" in str(out)
    assert st.snapshot()["malformed"] == 1


def test_oversized_length_prefix_is_rejected_not_allocated():
    # a hostile payload_len must be rejected from the PREFIX — the
    # reader must not try to read (or allocate) 64 MiB+
    pre = wire._PREAMBLE.pack(MAGIC, VERSION, K_REQ, 0, 0, 1, 0,
                              wire.MAX_PAYLOAD_LEN + 1)
    out, st = _read_with_stats(pre)
    assert isinstance(out, WireError) and "oversized" in str(out)
    assert st.snapshot()["malformed"] == 1
    pre = wire._PREAMBLE.pack(MAGIC, VERSION, K_REQ, 0, 0, 1,
                              wire.MAX_HEADER_LEN + 1, 0)
    out, _ = _read_with_stats(pre)
    assert isinstance(out, WireError)


def test_truncated_frames_every_cut_point():
    """EOF at any offset inside a frame is a counted malformed close —
    never a hang, never a crash.  (EOF exactly at a frame boundary is
    the one clean shutdown.)"""
    whole = b"".join(bytes(p) for p in frame_parts(
        K_REQ, 7, encode_qos_header(tenant="t"),
        [encode_request(OP_GENERATE, [1, 2, 3])]))
    clean, st = _read_with_stats(b"")
    assert clean is None and st.snapshot()["malformed"] == 0
    for cut in range(1, len(whole)):
        out, st = _read_with_stats(whole[:cut])
        assert isinstance(out, WireError), f"cut at {cut}: {out!r}"
        assert st.snapshot()["malformed"] == 1


def test_random_garbage_never_hangs_or_crashes():
    rng = np.random.default_rng(99)
    for _ in range(200):
        raw = rng.integers(0, 256,
                           int(rng.integers(1, 64))).astype(np.uint8)
        out, _ = _read_with_stats(raw.tobytes())
        assert out is None or isinstance(out, WireError)


def test_unknown_frame_kind_is_malformed():
    pre = wire._PREAMBLE.pack(MAGIC, VERSION, 200, 0, 0, 1, 0, 0)
    out, st = _read_with_stats(pre)
    assert isinstance(out, WireError)
    assert st.snapshot()["malformed"] == 1


# -- TokenRing ---------------------------------------------------------------

def test_token_ring_push_peek_consume_wraparound():
    ring = TokenRing(capacity=8)
    out = []
    ring.push_many([1, 2, 3, 4, 5])
    kind, start, view = ring.peek_batch(64)
    assert kind == "toks" and start == 0
    out.extend(int(t) for t in view)
    ring.consume(len(view))
    # wrap: head at 5, push 6 more — peek returns the CONTIGUOUS run
    # to the buffer end first, then the wrapped remainder
    ring.push_many([6, 7, 8, 9, 10, 11])
    while len(ring):
        _k, _s, view = ring.peek_batch(64)
        out.extend(int(t) for t in view)
        ring.consume(len(view))
    assert out == list(range(1, 12))


def test_token_ring_blocks_producer_until_consumed():
    ring = TokenRing(capacity=4)
    ring.push_many([1, 2, 3, 4])
    with pytest.raises(TimeoutError):
        ring.push_many([5], timeout=0.05)
    done = []

    def producer():
        ring.push_many([5, 6], timeout=5.0)
        done.append(True)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    _k, _s, view = ring.peek_batch(2)
    ring.consume(len(view))
    t.join(5.0)
    assert done == [True]


def test_token_ring_terminal_and_error():
    ring = TokenRing(capacity=4)
    ring.push_many([7])
    ring.finish({"finish": "eos"})
    k, _s, view = ring.peek_batch(8)
    assert k == "toks" and list(view) == [7]
    ring.consume(1)
    assert ring.peek_batch(8) == ("done", {"finish": "eos"})
    with pytest.raises(RuntimeError):
        ring.push_many([8])
    ring2 = TokenRing(capacity=4)
    ring2.fail(RuntimeError("slot died"))
    with pytest.raises(RuntimeError, match="slot died"):
        ring2.peek_batch(8)
    with pytest.raises(TimeoutError):
        TokenRing(capacity=4).peek_batch(8, timeout=0.05)


# -- LineCoalescer -----------------------------------------------------------

def test_line_coalescer_first_line_flushes_alone():
    writes = []
    co = LineCoalescer(writes.append, flush_tokens=4, flush_ms=1e4,
                       stats=WireStats())
    co.add(b"a\n")
    assert writes == [b"a\n"]           # first line: immediate
    co.add(b"b\n")
    co.add(b"c\n")
    assert writes == [b"a\n"]           # batching engaged
    co.add(b"d\n")
    co.add(b"e\n")
    assert writes == [b"a\n", b"b\nc\nd\ne\n"]  # count flush at 4
    co.add(b"f\n")
    co.add(b"g\n", urgent=True)         # terminal: flush now
    assert writes[-1] == b"f\ng\n"


# -- live engine fixtures ----------------------------------------------------

@pytest.fixture(scope="module")
def tiny_lm():
    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm

    seq = 16
    cfg = transformer_lm(vocab_size=64, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    return net, net.init_params(jax.random.PRNGKey(0)), seq


def _make_server(tiny_lm, wire_on=True):
    from singa_tpu.serve import (InferenceEngine, InferenceServer,
                                 ServeSpec)

    net, params, seq = tiny_lm
    spec = ServeSpec(buckets=((2, seq),), max_new_tokens=8,
                     batch_window_s=0.002, request_timeout_s=60.0,
                     cb="on", cb_slots=3, cb_block_len=4)
    eng = InferenceEngine(net, spec, params=params,
                          log_fn=lambda s: None)
    srv = InferenceServer(eng, port=0, wire_on=wire_on,
                          log_fn=lambda s: None)
    srv.start()
    return srv


@pytest.fixture(scope="module")
def wire_server(tiny_lm):
    """One shared live server (cb=on, wire on) for the tests that
    leave it intact; tests that stop listeners build their own."""
    srv = _make_server(tiny_lm, wire_on=True)
    yield srv
    srv.stop()


# -- binary server + handle over a real engine -------------------------------

def test_binary_stream_bit_identical_to_http(wire_server):
    from singa_tpu.serve import HttpEngineHandle

    host, port = wire_server.address
    prompt = np.arange(1, 5, dtype=np.int32)
    hh = HttpEngineHandle("e0", f"http://{host}:{port}")
    bh = BinaryEngineHandle("e0", wire_server.wire_address)
    try:
        u1 = hh.request("generate", prompt, timeout=30)
        u2 = bh.request("generate", prompt, timeout=30)
        assert u1["tokens"] == u2["tokens"]
        s1 = list(hh.request_stream(prompt, timeout=30, max_new=8))
        s2 = list(bh.request_stream(prompt, timeout=30, max_new=8))
        t1 = [ev["token"] for ev in s1 if "done" not in ev]
        t2 = [ev["token"] for ev in s2 if "done" not in ev]
        assert t1 == t2 == u1["tokens"]
        assert [ev["i"] for ev in s2 if "done" not in ev] == \
            list(range(8))
        assert s1[-1]["done"] and s2[-1]["done"]
        assert s1[-1]["finish"] == s2[-1]["finish"]
    finally:
        hh.close()
        bh.close()


def test_binary_multiplexes_streams_on_one_connection(wire_server):
    """Two concurrent streams ride ONE persistent socket (req_id
    demux) — and an early-closed stream cancels server-side without
    killing its neighbor."""
    bh = BinaryEngineHandle("e0", wire_server.wire_address)
    prompt = np.arange(1, 5, dtype=np.int32)
    try:
        g1 = bh.request_stream(prompt, timeout=30, max_new=8)
        g2 = bh.request_stream(prompt, timeout=30, max_new=8)
        first1 = next(g1)
        first2 = next(g2)
        assert first1["token"] == first2["token"]
        g1.close()                       # hedge-loser path: CANCEL
        rest = list(g2)
        assert rest[-1]["done"]
        assert bh._conn is not None and bh._conn.alive
    finally:
        bh.close()


def test_binary_error_mapping_admission(wire_server):
    bh = BinaryEngineHandle("e0", wire_server.wire_address)
    try:
        with pytest.raises(ValueError):
            bh.request("generate",
                       np.arange(100, dtype=np.int32), timeout=5)
        gen = bh.request_stream(np.arange(100, dtype=np.int32),
                                timeout=5)
        with pytest.raises(ValueError):
            next(gen)
    finally:
        bh.close()


def test_malformed_bytes_close_a_live_server_connection(wire_server):
    """A client that frames wrong gets its connection closed (counted)
    — and the server keeps serving other connections."""
    before = wire.STATS.snapshot()["malformed"]
    s = socket.create_connection(wire_server.wire_address,
                                 timeout=5.0)
    s.sendall(b"GET / HTTP/1.1\r\n\r\n")      # not our protocol
    s.settimeout(5.0)
    assert s.recv(64) == b""                  # closed, not hung
    s.close()
    assert wire.STATS.snapshot()["malformed"] > before
    # the listener survives: a well-formed client still works
    h = BinaryEngineHandle("e0", wire_server.wire_address)
    try:
        assert h.probe()["ok"]
    finally:
        h.close()


def test_binary_handle_reconnects_after_listener_restart(tiny_lm):
    from singa_tpu.serve.router import EngineUnavailable

    srv = _make_server(tiny_lm, wire_on=True)
    bh = BinaryEngineHandle("e0", srv.wire_address)
    try:
        assert bh.probe()["ok"]
        before = wire.STATS.snapshot()["reconnects"]
        srv._wire.stop()
        with pytest.raises(EngineUnavailable):
            bh.probe()
        srv._wire = BinaryTransportServer(
            srv, log_fn=lambda s: None).start()
        bh.address = srv.wire_address
        assert bh.probe()["ok"]
        assert wire.STATS.snapshot()["reconnects"] > before
    finally:
        bh.close()
        srv.stop()


# -- transport negotiation + fallback ----------------------------------------

def test_negotiation_upgrades_and_falls_back(tiny_lm):
    srv = _make_server(tiny_lm, wire_on=True)
    host, port = srv.address
    nh = NegotiatingEngineHandle("e0", f"http://{host}:{port}",
                                 log_fn=lambda s: None)
    prompt = np.arange(1, 5, dtype=np.int32)
    try:
        assert nh.transport == "http"    # before any probe
        h = nh.probe()
        assert h["transport"] == "binary" and h["wire_port"]
        ref = nh.request("generate", prompt, timeout=30)["tokens"]

        # kill ONLY the wire listener: the next binary attempt falls
        # back to HTTP in the SAME call — zero client-visible failures
        srv._wire.stop()
        srv._wire = None
        before = wire.STATS.snapshot()["fallbacks"]
        out = nh.request("generate", prompt, timeout=30)
        assert out["tokens"] == ref
        assert nh.transport == "http"
        assert wire.STATS.snapshot()["fallbacks"] == before + 1
        # ... and the stream path re-admits over HTTP the same way
        toks = [ev["token"]
                for ev in nh.request_stream(prompt, timeout=30,
                                            max_new=8)
                if "done" not in ev]
        assert toks == ref

        # the next probe is the re-discovery point
        srv._wire = BinaryTransportServer(
            srv, log_fn=lambda s: None).start()
        nh.probe()
        assert nh.transport == "binary"
        assert nh.request("generate", prompt,
                          timeout=30)["tokens"] == ref
    finally:
        nh.close()
        srv.stop()


def test_healthz_advertises_wire_port_only_when_listening(
        wire_server, tiny_lm):
    import urllib.request

    host, port = wire_server.address
    h = json.loads(urllib.request.urlopen(
        f"http://{host}:{port}/healthz", timeout=5).read())
    wa = wire_server.wire_address
    assert wa is not None and h["wire_port"] == wa[1]
    srv2 = _make_server(tiny_lm, wire_on=False)
    try:
        h2, p2 = srv2.address
        got = json.loads(urllib.request.urlopen(
            f"http://{h2}:{p2}/healthz", timeout=5).read())
        assert "wire_port" not in got
    finally:
        srv2.stop()


# -- mixed fleet: route / failover across the transport boundary -------------

def _adopted_fleet(urls, ws):
    from singa_tpu.serve import EngineFleet, RouterSpec

    rspec = RouterSpec(probe_period_s=0.1, hedge="off",
                       request_timeout_s=60.0, wal_group_tokens=4,
                       wal_group_ms=5.0, state_snapshot_s=0.1)
    return EngineFleet.adopt(urls, workspace=ws, router_spec=rspec,
                             log_fn=lambda s: None)


def _wait_transport(fleet, name, want, budget=10.0):
    deadline = time.monotonic() + budget
    h = fleet.router.handle_for(name)
    while time.monotonic() < deadline and h.transport != want:
        time.sleep(0.05)
    return h.transport


def test_mixed_fleet_failover_crosses_transport_boundary(tiny_lm):
    """A fleet mixing a binary-capable engine and an HTTP-only engine
    routes across the boundary, and a mid-stream kill of the binary
    engine splices the stream exactly-once onto the HTTP-only sibling
    via the session machinery — the final token sequence is
    BIT-IDENTICAL to an uninterrupted reference."""
    from singa_tpu.utils.checkpoint import CheckpointManager

    net, params, seq = tiny_lm
    a = _make_server(tiny_lm, wire_on=True)     # binary-capable
    b = _make_server(tiny_lm, wire_on=False)    # HTTP-only
    with tempfile.TemporaryDirectory() as ws:
        CheckpointManager(ws, log_fn=lambda s: None).save(
            1, params, {"t": np.zeros(())}, health={"verdict": "ok"})
        urls = [f"http://{h}:{p}" for h, p in (a.address, b.address)]
        fleet = _adopted_fleet(urls, ws)
        try:
            fleet.start()
            assert _wait_transport(fleet, "engine-0",
                                   "binary") == "binary"
            assert fleet.router.handle_for("engine-1").transport == \
                "http"

            prompt = np.arange(1, 5, dtype=np.int32)
            ref = [ev["token"]
                   for ev in fleet.generate_stream(prompt, max_new=8)
                   if "token" in ev]
            assert len(ref) == 8

            # unary traffic crosses the boundary freely: concurrent
            # requests spread over BOTH transports (sequential calls
            # would all land on the least-loaded tie winner)
            outs = []

            def one():
                outs.append(fleet.generate(prompt)["engine"])

            threads = [threading.Thread(target=one)
                       for _ in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
            assert len(outs) == 12       # every request succeeded
            assert set(outs) <= {"engine-0", "engine-1"}

            # mid-stream kill of the binary worker: the session layer
            # must splice the remainder from the HTTP-only sibling
            stream = fleet.generate_stream(prompt, max_new=8)
            seen, killed = [], False
            for ev in stream:
                if "token" in ev:
                    seen.append(ev["token"])
                if len(seen) == 3 and not killed:
                    killed = True
                    a.stop()             # the whole binary worker
            assert seen == ref           # exactly once, bit-identical
        finally:
            fleet.stop()
            b.stop()
            try:
                a.stop()
            except Exception:  # noqa: BLE001 — may already be down
                pass


def test_wire_listener_death_does_not_lose_inflight_stream(tiny_lm):
    """The binary listener of an engine dies mid-stream (the worker
    and its HTTP surface stay up): the stream's wire break feeds the
    router's failover machinery, the transport degrades to HTTP, and
    the client sees every token exactly once."""
    from singa_tpu.utils.checkpoint import CheckpointManager

    net, params, seq = tiny_lm
    a = _make_server(tiny_lm, wire_on=True)
    b = _make_server(tiny_lm, wire_on=False)
    with tempfile.TemporaryDirectory() as ws:
        CheckpointManager(ws, log_fn=lambda s: None).save(
            1, params, {"t": np.zeros(())}, health={"verdict": "ok"})
        urls = [f"http://{h}:{p}" for h, p in (a.address, b.address)]
        fleet = _adopted_fleet(urls, ws)
        try:
            fleet.start()
            assert _wait_transport(fleet, "engine-0",
                                   "binary") == "binary"
            prompt = np.arange(1, 5, dtype=np.int32)
            ref = [ev["token"]
                   for ev in fleet.generate_stream(prompt, max_new=8)
                   if "token" in ev]

            h0 = fleet.router.handle_for("engine-0")
            stream = fleet.generate_stream(prompt, max_new=8)
            seen, killed = [], False
            for ev in stream:
                if "token" in ev:
                    seen.append(ev["token"])
                if len(seen) == 2 and not killed:
                    killed = True
                    a._wire.stop()       # ONLY the binary listener
                    a._wire = None
            assert seen == ref           # exactly once, no loss
            # engine-0's data plane degraded to HTTP (its worker and
            # debug surface never went away)
            assert h0.transport == "http"
        finally:
            fleet.stop()
            b.stop()
            a.stop()


# -- wire.frame fault site ---------------------------------------------------

def test_wire_frame_fault_degrades_to_http_not_failure(tiny_lm):
    """An injected frame drop / corruption / tear on the binary path
    is a counted transport failure the negotiating handle absorbs by
    falling back to HTTP — never a client-visible error, never a
    hang."""
    from singa_tpu.utils.faults import FaultSchedule, inject

    srv = _make_server(tiny_lm, wire_on=True)
    host, port = srv.address
    prompt = np.arange(1, 5, dtype=np.int32)
    try:
        for kind in ("error", "corrupt", "torn"):
            nh = NegotiatingEngineHandle(
                "e0", f"http://{host}:{port}", connect_timeout_s=3.0,
                log_fn=lambda s: None)
            try:
                nh.probe()
                assert nh.transport == "binary"
                before = wire.STATS.snapshot()["faulted_frames"]
                with inject(
                        FaultSchedule.parse(f"wire.frame@0:{kind}")):
                    out = nh.request("generate", prompt, timeout=30)
                assert len(out["tokens"]) == 8, kind
                assert wire.STATS.snapshot()["faulted_frames"] > \
                    before, kind
            finally:
                nh.close()
    finally:
        srv.stop()


def test_wire_frame_corrupt_counts_malformed_at_receiver(tiny_lm):
    """A corrupted outbound frame (flipped magic) must be counted
    `wire_malformed_total` by the RECEIVER and close that connection
    — the honest-error contract of the fuzz satellite, on a live
    server."""
    from singa_tpu.utils.faults import FaultSchedule, inject

    srv = _make_server(tiny_lm, wire_on=True)
    before = wire.STATS.snapshot()["malformed"]
    try:
        with inject(FaultSchedule.parse("wire.frame@0:corrupt")):
            with pytest.raises(Exception):
                # HELLO goes out corrupted -> server counts + closes
                # -> handshake fails
                BinaryEngineHandle("e0", srv.wire_address,
                                   connect_timeout_s=3.0).probe()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                wire.STATS.snapshot()["malformed"] <= before:
            time.sleep(0.02)
        assert wire.STATS.snapshot()["malformed"] > before
    finally:
        srv.stop()


# -- satellite: HttpEngineHandle keep-alive reuse ----------------------------

def _stub_http(handler_cls, server_cls=None):
    from http.server import ThreadingHTTPServer

    cls = server_cls or ThreadingHTTPServer
    httpd = cls(("127.0.0.1", 0), handler_cls)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_http_handle_keepalive_reuses_one_socket():
    """N sequential unary calls and probes ride ONE TCP connection —
    per-request connection setup is off the hot path.  The stub
    server counts accepted connections; an error reply must NOT
    poison the pooled socket (the body is drained, keep-alive
    holds)."""
    from http.server import (BaseHTTPRequestHandler,
                             ThreadingHTTPServer)

    from singa_tpu.serve.batcher import Overloaded
    from singa_tpu.serve.router import HttpEngineHandle

    conns = []

    class CountingServer(ThreadingHTTPServer):
        def process_request(self, request, client_address):
            conns.append(client_address)
            super().process_request(request, client_address)

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def _json(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._json(200, {"ok": True, "status": "ok", "step": 1,
                             "queue_depth": 0})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            self._json(503, {"error": "overloaded",
                             "retry_after": 0.1})

    httpd = _stub_http(H, CountingServer)
    h = HttpEngineHandle(
        "e0", f"http://127.0.0.1:{httpd.server_address[1]}")
    try:
        for _ in range(10):
            h.probe()                    # 2 GETs each
        for _ in range(10):
            with pytest.raises(Overloaded):
                h.request("generate", [1, 2])   # 503 + drained body
        for _ in range(10):
            h.stats_snapshot()
        assert len(conns) == 1, \
            f"expected ONE reused connection, server saw {len(conns)}"
    finally:
        h.close()
        httpd.shutdown()
        httpd.server_close()


def test_http_handle_keepalive_survives_peer_close():
    """A peer that closes after every reply (Connection: close) must
    not poison the pool or surface errors — the handle detects the
    non-reusable exchange and never pools that socket."""
    from http.server import BaseHTTPRequestHandler

    from singa_tpu.serve.router import HttpEngineHandle

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            body = json.dumps({"ok": True, "status": "ok",
                               "step": 1, "queue_depth": 0}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)
            self.close_connection = True

    httpd = _stub_http(H)
    h = HttpEngineHandle(
        "e0", f"http://127.0.0.1:{httpd.server_address[1]}")
    try:
        for _ in range(5):
            assert h.stats_snapshot()["ok"]
        assert len(h._pool) == 0         # close-announced: not pooled
    finally:
        h.close()
        httpd.shutdown()
        httpd.server_close()


def test_http_handle_pool_is_bounded():
    """Pooled sockets are capped at POOL_CAP — a concurrent burst
    must not grow an unbounded fd set."""
    from http.server import BaseHTTPRequestHandler

    from singa_tpu.serve.router import HttpEngineHandle

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            body = b'{"ok": true}'
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = _stub_http(H)
    h = HttpEngineHandle(
        "e0", f"http://127.0.0.1:{httpd.server_address[1]}")
    try:
        threads = [threading.Thread(
            target=lambda: h._call("GET", "/healthz"))
            for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10.0)
        assert len(h._pool) <= h.POOL_CAP
    finally:
        h.close()
        httpd.shutdown()
        httpd.server_close()
