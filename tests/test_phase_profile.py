"""Device-side fwd/bwd/update phase report (worker.h:91-114 parity):
the reference timed each phase around its call; here the split comes
from a one-shot profiler trace attributed through HLO metadata and then
rides every TimerInfo display line."""

import jax
import numpy as np

from singa_tpu.config.schema import model_config_from_dict
from singa_tpu.core.trainer import Trainer
from singa_tpu.data.synthetic import synthetic_image_batches
from singa_tpu.utils.profiler import classify_phase


def _cfg():
    return model_config_from_dict({
        "name": "m", "train_steps": 6, "display_frequency": 2,
        "updater": {"type": "kSGD", "base_learning_rate": 0.1,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kShardData",
             "data_param": {"batchsize": 8}},
            {"name": "mnist", "type": "kMnistImage", "srclayers": "data"},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip", "type": "kInnerProduct", "srclayers": "mnist",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "weight"}, {"name": "bias"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip", "label"]}]}})


def test_classify_phase_tags():
    assert classify_phase(
        "jit(f)/jvp(net)/dot_general  [linear.py:10]") == "fwd"
    assert classify_phase(
        "jit(f)/transpose(jvp(net))/dot_general  [linear.py:10]") == "bwd"
    assert classify_phase(
        "jit(f)/while/body/mul  [updater.py:150]") == "update"


def test_run_reports_device_phase_shares(tmp_path):
    logs = []
    tr = Trainer(_cfg(), {"data": {"pixel": (28, 28), "label": ()}},
                 donate=False, log_fn=logs.append)
    tr.phase_profile = True
    p, o = tr.init(0)
    tr.run(p, o, synthetic_image_batches(8))
    shares = tr.timer.phase_shares
    assert shares is not None and shares, shares
    assert 0 < shares["bwd"] < 1 and 0 < shares["fwd"] < 1
    phase_sum = sum(v for k, v in shares.items() if k != "coverage")
    assert abs(phase_sum - 1.0) < 1e-6
    # coverage rides along so the report can qualify fusion blur
    assert 0 < shares["coverage"] <= 1.0, shares
    timer_lines = [l for l in logs if "Time per step" in l]
    assert timer_lines and all("[device: fwd" in l for l in timer_lines)
    assert all("% of device time attributed]" in l for l in timer_lines)


def test_profile_phases_preserves_training_state():
    """profile_phases must not consume donated buffers: params passed in
    stay usable afterwards."""
    tr = Trainer(_cfg(), {"data": {"pixel": (28, 28), "label": ()}},
                 donate=True, log_fn=lambda s: None)
    p, o = tr.init(0)
    batch = next(synthetic_image_batches(8))
    tr.profile_phases(p, o, batch)
    # state still alive: a real step runs on the same arrays
    p2, o2, m = tr.train_step(p, o, batch, 0, jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss"]))


def test_fused_eval_scan_matches_per_batch():
    """evaluate() fuses full chunks into one lax.scan dispatch; the
    averaged metrics must equal the per-batch path on the same stream."""
    from singa_tpu.config import load_model_config

    cfg = load_model_config("examples/mnist/conv.conf")
    cfg.train_steps = 1
    tr = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                 donate=False, log_fn=lambda s: None)
    assert tr.test_step is not None
    p, _ = tr.init(0)
    mk = lambda: synthetic_image_batches(16, seed=5, stream_seed=9)
    a = tr.evaluate(p, mk(), 30, tr.test_step)            # 25-scan + 5
    b = tr.evaluate(p, mk(), 30, tr.test_step, scan_chunk=1)
    assert set(a) == set(b)
    for k in a:
        assert abs(a[k] - b[k]) < 1e-5, (k, a[k], b[k])
