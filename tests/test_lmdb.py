"""LMDB ingestion (VERDICT r2 item 6): read a real on-disk LMDB
environment (fixture-written from the format spec), convert it to a
shard, and train on it.  Reference bar: layer.cc:237-328 (caffe LMDB
cursor walk feeding Datum records)."""

import os

import numpy as np
import pytest

from singa_tpu.data.lmdb_reader import (LMDBFormatError, iter_lmdb,
                                        lmdb_entry_count)
from singa_tpu.data.records import Datum

from lmdb_fixture import write_lmdb


def _items(n, vsize=64, seed=0):
    rng = np.random.default_rng(seed)
    return [(b"%08d" % i, rng.bytes(vsize)) for i in range(n)]


def test_roundtrip_single_leaf(tmp_path):
    items = _items(8)
    write_lmdb(str(tmp_path), items)
    assert list(iter_lmdb(str(tmp_path))) == items
    assert lmdb_entry_count(str(tmp_path)) == 8


def test_roundtrip_multi_leaf_branch(tmp_path):
    items = _items(200, vsize=100)       # forces several leaves + branch
    write_lmdb(str(tmp_path), items)
    assert list(iter_lmdb(str(tmp_path))) == items


def test_roundtrip_overflow_values(tmp_path):
    # 3KB values on 4KB pages — the caffe Datum case — plus a >1-page
    # value to exercise multi-page overflow chains
    items = _items(10, vsize=3000) + [(b"zzbig", os.urandom(9000))]
    write_lmdb(str(tmp_path), items)
    got = dict(iter_lmdb(str(tmp_path)))
    assert got == dict(items)


def test_key_order_is_btree_order(tmp_path):
    items = _items(50, vsize=500)
    write_lmdb(str(tmp_path), list(reversed(items)))
    assert [k for k, _ in iter_lmdb(str(tmp_path))] == sorted(
        k for k, _ in items)


def test_empty_env(tmp_path):
    write_lmdb(str(tmp_path), [])
    assert list(iter_lmdb(str(tmp_path))) == []


def test_garbage_fails_loud(tmp_path):
    p = tmp_path / "data.mdb"
    p.write_bytes(os.urandom(8192))
    with pytest.raises(LMDBFormatError):
        list(iter_lmdb(str(tmp_path)))


def test_datum_values_decode(tmp_path):
    rng = np.random.default_rng(1)
    items = []
    for i in range(6):
        d = Datum(channels=3, height=8, width=8,
                  data=rng.bytes(3 * 8 * 8), label=i % 3)
        items.append((b"%08d" % i, d.encode()))
    write_lmdb(str(tmp_path), items)
    decoded = [Datum.decode(v) for _, v in iter_lmdb(str(tmp_path))]
    assert [d.label for d in decoded] == [0, 1, 2, 0, 1, 2]
    assert all(len(d.data) == 192 for d in decoded)


def test_encoded_datum_fails_loud(tmp_path):
    d = Datum(channels=3, height=8, width=8, data=b"\xff\xd8jpeg",
              encoded=True)
    write_lmdb(str(tmp_path), [(b"00000000", d.encode())])
    from singa_tpu.data.pipeline import lmdb_batches
    with pytest.raises(ValueError, match="encoded"):
        next(lmdb_batches(str(tmp_path), 1))


def test_empty_env_as_train_source_fails_loud(tmp_path):
    write_lmdb(str(tmp_path), [])
    from singa_tpu.data.pipeline import lmdb_batches
    with pytest.raises(ValueError, match="no usable"):
        next(lmdb_batches(str(tmp_path), 4, loop=True))


def test_convert_lmdb_to_shard_and_train(tmp_path):
    """loader convert-lmdb + kLMDBData read path: build an env of
    Datums, convert to a shard, then resolve a kLMDBData config
    directly against the env and take real batches from it."""
    import jax

    from singa_tpu.config.schema import model_config_from_dict
    from singa_tpu.data import resolve_data_source
    from singa_tpu.data.shard import Shard
    from singa_tpu.tools import loader

    rng = np.random.default_rng(2)
    env = tmp_path / "env"
    items = []
    for i in range(24):
        d = Datum(channels=3, height=8, width=8,
                  data=rng.bytes(192), label=i % 10)
        items.append((b"%08d" % i, d.encode()))
    write_lmdb(str(env), items)

    # conversion tool
    out = tmp_path / "shard"
    out.mkdir()
    rc = loader.main(["convert-lmdb", str(env), str(out)])
    assert rc == 0
    shard = Shard(str(out), Shard.KREAD)
    assert sum(1 for _ in shard) == 24
    shard.close()

    # direct kLMDBData read path
    cfg = model_config_from_dict({
        "name": "lmdbtest", "train_steps": 2,
        "updater": {"type": "kSGD", "base_learning_rate": 0.1,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kLMDBData",
             "data_param": {"path": str(env), "batchsize": 8}},
            {"name": "rgb", "type": "kRGBImage", "srclayers": "data",
             "rgbimage_param": {"scale": 1.0}},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip", "type": "kInnerProduct", "srclayers": "rgb",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "weight"}, {"name": "bias"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip", "label"]},
        ]}})
    train_iter, _ = resolve_data_source(cfg, 8)
    batch = next(iter(train_iter))
    px = np.asarray(batch["data"]["pixel"])
    assert px.shape == (8, 3, 8, 8)
    lbl = np.asarray(batch["data"]["label"])
    assert list(lbl) == [i % 10 for i in range(8)]


@pytest.mark.parametrize("page_size", [512, 1024, 4096, 16384, 65536])
def test_roundtrip_across_page_sizes(tmp_path, page_size):
    """The reader detects the environment's page size from the meta
    pages — all standard LMDB sizes round-trip."""
    items = _items(40, vsize=page_size // 8, seed=3)
    write_lmdb(str(tmp_path), items, page_size=page_size)
    assert list(iter_lmdb(str(tmp_path))) == items


def test_values_straddling_overflow_threshold(tmp_path):
    """Values on both sides of the in-page/overflow boundary in ONE
    env: every size from tiny to multi-page must survive."""
    rng = np.random.default_rng(9)
    items = [(b"%08d" % i, rng.bytes(size))
             for i, size in enumerate(
                 [1, 100, 1900, 1990, 2000, 2100, 4000, 4096, 5000,
                  12000])]
    write_lmdb(str(tmp_path), items)
    got = dict(iter_lmdb(str(tmp_path)))
    assert {k: len(v) for k, v in got.items()} == {
        k: len(v) for k, v in items}
    assert got == dict(items)


def test_binary_keys_sort_by_memcmp(tmp_path):
    """B-tree order is raw-byte order, not text order."""
    items = [(bytes([b]), b"v%d" % b) for b in (0, 1, 127, 128, 255)]
    write_lmdb(str(tmp_path), list(reversed(items)))
    assert [k for k, _ in iter_lmdb(str(tmp_path))] == [
        k for k, _ in items]


def test_small_env_fills_batches_across_epochs(tmp_path):
    """An env with fewer records than the batch still yields: partial
    batches carry across epoch boundaries in loop mode."""
    from singa_tpu.data.pipeline import lmdb_batches
    items = []
    rng = np.random.default_rng(5)
    for i in range(5):
        d = Datum(channels=1, height=4, width=4, data=rng.bytes(16),
                  label=i)
        items.append((b"%08d" % i, d.encode()))
    write_lmdb(str(tmp_path), items)
    it = lmdb_batches(str(tmp_path), 8, loop=True)
    batch = next(it)
    assert np.asarray(batch["data"]["pixel"]).shape[0] == 8
    # second batch proves the stream keeps flowing
    assert np.asarray(next(it)["data"]["label"]).shape[0] == 8


def test_large_random_skip_carries_across_passes(tmp_path):
    """random_skip >= entry count must NOT raise: leftover skip
    carries into the next pass (shard_batches contract)."""
    from singa_tpu.data.pipeline import lmdb_batches
    rng = np.random.default_rng(6)
    items = [(b"%08d" % i, Datum(channels=1, height=4, width=4,
                                 data=rng.bytes(16), label=i).encode())
             for i in range(10)]
    write_lmdb(str(tmp_path), items)
    it = lmdb_batches(str(tmp_path), 4, loop=True, random_skip=25,
                      seed=3)
    batch = next(it)     # must eventually yield, not raise or spin
    assert np.asarray(batch["data"]["pixel"]).shape[0] == 4


def test_small_shard_fills_batches_across_epochs(tmp_path):
    """Same carry contract for shard_batches (the bug existed there
    too)."""
    from singa_tpu.data.pipeline import shard_batches
    from singa_tpu.data.records import Record, SingleLabelImageRecord
    from singa_tpu.data.shard import Shard

    import os as _os
    _os.makedirs(tmp_path / "sh", exist_ok=True)
    rng = np.random.default_rng(7)
    with Shard(str(tmp_path / "sh"), Shard.KCREATE) as sh:
        for i in range(3):
            rec = Record(image=SingleLabelImageRecord(
                shape=[1, 4, 4], label=i, pixel=rng.bytes(16)))
            sh.insert(b"%08d" % i, rec.encode())
    it = shard_batches(str(tmp_path / "sh"), 8, loop=True)
    assert np.asarray(next(it)["data"]["pixel"]).shape[0] == 8


def test_empty_shard_fails_loud_in_loop_mode(tmp_path):
    """An empty shard.dat as a loop-mode source raises instead of
    spinning hot forever (the same guard lmdb_batches has)."""
    from singa_tpu.data.pipeline import shard_batches
    from singa_tpu.data.shard import Shard

    import os as _os
    _os.makedirs(tmp_path / "empty", exist_ok=True)
    with Shard(str(tmp_path / "empty"), Shard.KCREATE):
        pass
    with pytest.raises(ValueError, match="no usable"):
        next(shard_batches(str(tmp_path / "empty"), 4, loop=True))


def test_oversized_skip_warns_once(tmp_path, capsys):
    from singa_tpu.data.pipeline import lmdb_batches
    rng = np.random.default_rng(11)
    items = [(b"%08d" % i, Datum(channels=1, height=4, width=4,
                                 data=rng.bytes(16), label=i).encode())
             for i in range(4)]
    write_lmdb(str(tmp_path), items)
    # precondition: the generator's seeded draw must exceed the
    # dataset, else no pass is fully consumed and no warning fires
    draw = np.random.default_rng(1).integers(0, 31)
    assert draw > len(items), draw
    it = lmdb_batches(str(tmp_path), 2, loop=True, random_skip=30,
                      seed=1)
    next(it)
    err = capsys.readouterr().err
    assert err.count("consumed an entire pass") == 1


def test_mixed_skip_and_imageless_pass_raises_accurately(tmp_path):
    """A pass that is part skip, part image-less records must not
    blame random_skip — once the skip budget exhausts, the accurate
    'no usable image records' error surfaces."""
    from singa_tpu.data.pipeline import lmdb_batches
    items = [(b"%08d" % i,
              Datum(label=i).encode())           # image-less Datums
             for i in range(5)]
    write_lmdb(str(tmp_path), items)
    it = lmdb_batches(str(tmp_path), 2, loop=True, random_skip=3,
                      seed=0)
    with pytest.raises(ValueError, match="no usable"):
        next(it)
