"""Performance observatory tests (ISSUE 15): CompileWatch counting
and hit/miss labeling, the recompile-anomaly event + flight-recorder
trigger (with cooldown), MemoryWatch's analytic fallback arithmetic
against a known KV-pool geometry, CostWatch's no-recompile property,
readiness-timer latch monotonicity, the process-level collector, and
the labeled-Sample exposition round trip.

Cost control: everything here is host-side except one tiny jit (one
add) proving `compiled_flops` still accepts a jit-wrapped callable."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu import obs
from singa_tpu.core.net import build_net
from singa_tpu.models.transformer import transformer_lm
from singa_tpu.obs import perf
from singa_tpu.obs.metrics import MetricsRegistry, parse_prometheus
from singa_tpu.serve.kvcache import init_pools, pool_bytes
from singa_tpu.utils.flops import compiled_flops, cost_metrics

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _fresh_watch():
    """Each test gets its own PerfWatch (the module API is a
    process-global singleton) and no leaked obs session."""
    obs.disable()
    perf.reset()
    yield
    obs.disable()
    perf.reset()


# -- CompileWatch ------------------------------------------------------------

def test_compile_counts_and_cache_labels():
    with perf.compile_span("progA", geometry="b2_p16"):
        pass
    with perf.compile_span("progA"):
        pass
    with perf.compile_span("progB"):
        pass
    perf.lookup_hit("progA")
    perf.lookup_hit("progA")
    snap = perf.snapshot()
    assert snap["compiles"] == {"progA": 2, "progB": 1}
    assert snap["compiles_total"] == 3
    assert snap["cache"]["progA:hit"] == 2
    assert snap["cache"]["progA:miss"] == 2
    assert snap["cache"]["progB:miss"] == 1
    assert snap["compile_count"] == 3
    # the labeled series fan out per program in the exposition
    reg = MetricsRegistry()
    perf.register_into(reg)
    got = parse_prometheus(reg.render_prometheus())
    assert got['singa_compiles_total{program="progA"}'] == 2
    assert got['singa_compiles_total{program="progB"}'] == 1
    assert got['singa_compile_cache_total{program="progA",'
               'result="hit"}'] == 2
    assert got["singa_compile_seconds_count"] == 3


def test_register_into_survives_reset():
    reg = MetricsRegistry()
    perf.register_into(reg)
    perf.reset()                      # swaps the singleton
    with perf.compile_span("after_reset"):
        pass
    got = parse_prometheus(reg.render_prometheus())
    assert got['singa_compiles_total{program="after_reset"}'] == 1


def test_warm_scope_anomaly_accounting():
    perf.mark_warm("eng1", "generate")
    # other family / other scope: lazy compiles, not violations
    with perf.compile_span("predict", scope="eng1", family="predict"):
        pass
    with perf.compile_span("generate", scope="eng2",
                           family="generate"):
        pass
    assert perf.snapshot()["anomalies"] == 0
    # same (scope, family): PR 8's invariant is broken
    with perf.compile_span("generate", scope="eng1",
                           family="generate"):
        pass
    snap = perf.snapshot()
    assert snap["anomalies"] == 1
    assert [r for r in snap["records"] if r["anomaly"]] \
        == [{"program": "generate", "geometry": "", "scope": "eng1",
             "seconds": snap["records"][-1]["seconds"],
             "anomaly": True}]


def test_recompile_anomaly_event_and_flightrec_trigger(tmp_path):
    events = tmp_path / "events.jsonl"
    rec_dir = tmp_path / "rec"
    spec = obs.ObsSpec(events=str(events), flightrec=str(rec_dir))
    with obs.session(spec) as o:
        o.flightrec.cooldown_s = 3600.0   # suppress the second dump
        perf.mark_warm("eng", "generate")
        with perf.compile_span("generate", scope="eng",
                               family="generate"):
            pass
        with perf.compile_span("generate", scope="eng",
                               family="generate"):
            pass
        assert perf.snapshot()["anomalies"] == 2
        dumps = glob.glob(str(rec_dir / "flightrec-recompile-*.json"))
        assert len(dumps) == 1            # cooldown rate-limited
        with open(dumps[0]) as f:
            dump = json.load(f)
        assert dump["trigger"] == "recompile"
        # the perf context rides along with the evidence
        assert dump["perf"]["anomalies"] >= 1
        assert "hbm_watermark_bytes" in dump["perf"]
        # cooldown over -> the next anomaly dumps again
        o.flightrec.cooldown_s = 0.0
        with perf.compile_span("generate", scope="eng",
                               family="generate"):
            pass
        assert len(glob.glob(
            str(rec_dir / "flightrec-recompile-*.json"))) == 2
    kinds = [json.loads(line)["kind"]
             for line in events.read_text().splitlines()]
    assert kinds.count("perf.recompile_anomaly") == 3


# -- readiness latches -------------------------------------------------------

def test_readiness_latch_first_call_wins():
    assert perf.snapshot()["serving_ready_s"] is None
    a = perf.mark_serving_ready()
    b = perf.mark_serving_ready()
    assert a == b > 0
    t1 = perf.mark_training_ready()
    t2 = perf.mark_training_ready()
    assert t1 == t2 > 0
    snap = perf.snapshot()
    assert snap["serving_ready_s"] == a
    assert snap["training_ready_s"] == t1
    reg = MetricsRegistry()
    perf.register_into(reg)
    got = parse_prometheus(reg.render_prometheus())
    assert got["singa_restart_to_serving_seconds"] == pytest.approx(a)
    assert got["singa_restart_to_training_seconds"] == pytest.approx(t1)


# -- MemoryWatch -------------------------------------------------------------

def test_analytic_pool_bytes_matches_real_pools():
    cfg = transformer_lm(vocab_size=32, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=16,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (16,), "target": (16,)}})
    num_blocks, block_len = 9, 4
    pools = init_pools(net, num_blocks, block_len)
    real = sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for layer in pools.values() for a in layer.values())
    analytic = pool_bytes(net, num_blocks, block_len)
    # 2 layers x {k,v} x (9, 4 kv_heads, 4, 8) x float32
    assert analytic == real == 2 * 2 * 9 * 4 * 4 * 8 * 4


def test_memory_components_and_watermark():
    perf.set_memory("kv_pool", 1000, scope="eng1")
    perf.set_memory("kv_pool", 500, scope="eng2")
    perf.set_memory_tree("params", {"w": np.zeros((10, 10),
                                                  np.float32)})
    snap = perf.snapshot()
    assert snap["memory_components"] == {"kv_pool": 1500,
                                         "params": 400}
    assert snap["hbm_watermark_bytes"] == 1900
    # shrinking a component never lowers the watermark
    perf.set_memory("kv_pool", 100, scope="eng1")
    snap = perf.snapshot()
    assert snap["memory_components"]["kv_pool"] == 600
    assert snap["hbm_watermark_bytes"] == 1900
    reg = MetricsRegistry()
    perf.register_into(reg)
    got = parse_prometheus(reg.render_prometheus())
    assert got['singa_hbm_analytic_bytes{component="kv_pool"}'] == 600
    assert got["singa_hbm_analytic_total_bytes"] == 1000
    assert got["singa_hbm_watermark_bytes"] == 1900


# -- CostWatch ---------------------------------------------------------------

class _CompiledGuard:
    """Stands in for a jit(...).lower(...).compile() result; any
    attempt to re-lower (i.e. recompile) trips the test."""

    def cost_analysis(self):
        return [{"flops": 123.0, "bytes accessed": 456.0,
                 "not_numeric": "x"}]

    def lower(self, *a, **k):       # pragma: no cover — the property
        raise AssertionError("CostWatch triggered a recompile")


def test_costwatch_never_recompiles():
    guard = _CompiledGuard()
    assert cost_metrics(guard) == {"flops": 123.0,
                                   "bytes accessed": 456.0}
    assert compiled_flops(guard) == 123.0
    entry = perf.harvest("prog", guard)
    assert entry == {"flops": 123.0, "bytes": 456.0}
    perf.observe_step("prog", 0.5)
    reg = MetricsRegistry()
    perf.register_into(reg)
    got = parse_prometheus(reg.render_prometheus())
    assert got['singa_program_flops{program="prog"}'] == 123.0
    assert got['singa_program_bytes{program="prog"}'] == 456.0
    assert got['singa_program_arith_intensity{program="prog"}'] == \
        pytest.approx(123.0 / 456.0)


def test_compiled_flops_still_accepts_jitted_callable():
    jitted = jax.jit(lambda x: x @ x)
    got = compiled_flops(jitted, jnp.ones((4, 4), jnp.float32))
    assert got is None or got > 0   # backend cost model may omit flops


# -- process collector + exposition ------------------------------------------

def test_process_collector_on_registry():
    reg = MetricsRegistry()
    perf.register_process_into(reg)
    got = parse_prometheus(reg.render_prometheus())
    assert got["singa_process_threads"] >= 1
    assert got["singa_process_uptime_seconds"] > 0
    if os.path.exists("/proc/self/statm"):
        assert got["singa_process_rss_bytes"] > 0
        assert got["singa_process_open_fds"] > 0


def test_labeled_samples_render_one_header_per_name():
    with perf.compile_span("a"):
        pass
    with perf.compile_span("b"):
        pass
    reg = MetricsRegistry()
    perf.register_into(reg)
    text = reg.render_prometheus()
    assert text.count("# TYPE singa_compiles_total counter") == 1
    got = parse_prometheus(text)
    assert got['singa_compiles_total{program="a"}'] == 1
    assert got['singa_compiles_total{program="b"}'] == 1
