"""Partitioner parity: LayerProto.partition_type → GSPMD constraints.

Reference: neuralnet.cc:198-323 rewrites the graph per-layer from
partition_type, inserting one of 9 connector patterns for every
(src partition) × (dst partition) combination (kNone, kDataPartition,
kLayerPartition).  Here the same intent is a sharding constraint per
activation and XLA compiles the data movement; these tests mirror the
9 cases by asserting numeric equality (loss AND grads) with the
unpartitioned net on the virtual 8-CPU mesh (SURVEY §7 hard part #1).
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.config.schema import model_config_from_dict
from singa_tpu.core.net import build_net
from singa_tpu.parallel.mesh import make_mesh
from singa_tpu.parallel.partition import (batch_shardings, param_shardings,
                                          shard_batch)

PTYPES = ["kNone", "kDataPartition", "kLayerPartition"]
SHAPES = {"data": {"pixel": (16,), "label": ()}}


def _cfg(src_ptype, dst_ptype):
    layers = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": 8}},
        {"name": "label", "type": "kLabel", "srclayers": "data"},
        {"name": "img", "type": "kMnistImage", "srclayers": "data",
         "mnist_param": {"norm_a": 1.0}},
        {"name": "fc_src", "type": "kInnerProduct", "srclayers": "img",
         "partition_type": src_ptype,
         "inner_product_param": {"num_output": 32},
         "param": [{"name": "weight", "init_method": "kUniform",
                    "low": -0.1, "high": 0.1},
                   {"name": "bias"}]},
        {"name": "act", "type": "kTanh", "srclayers": "fc_src",
         "partition_type": src_ptype},
        {"name": "fc_dst", "type": "kInnerProduct", "srclayers": "act",
         "partition_type": dst_ptype,
         "inner_product_param": {"num_output": 16},
         "param": [{"name": "weight", "init_method": "kUniform",
                    "low": -0.1, "high": 0.1},
                   {"name": "bias"}]},
        {"name": "loss", "type": "kSoftmaxLoss",
         "srclayers": ["fc_dst", "label"]},
    ]
    return model_config_from_dict({
        "name": f"part-{src_ptype}-{dst_ptype}", "train_steps": 1,
        "updater": {"type": "kSGD", "base_learning_rate": 0.1,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers}})


def _batch(rng):
    return {"data": {
        "pixel": jnp.asarray(rng.standard_normal((8, 16)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (8,)))}}


@pytest.mark.parametrize("src,dst", list(itertools.product(PTYPES, PTYPES)))
def test_nine_connector_cases_match_unpartitioned(src, dst):
    """Each of the reference partitioner's 9 src→dst combinations
    computes identical loss and param grads to the flat net."""
    mesh = make_mesh(jax.devices(), data=2, model=2, seq=2)
    cfg = _cfg(src, dst)
    batch = _batch(np.random.default_rng(7))

    net = build_net(cfg, "kTrain", SHAPES)
    params = net.init_params(jax.random.PRNGKey(0))

    def loss_flat(p, b):
        return net.apply(p, b, train=True)[0]

    def loss_mesh(p, b):
        return net.apply(p, b, train=True, mesh=mesh)[0]

    l0, g0 = jax.jit(jax.value_and_grad(loss_flat))(params, batch)

    p_sh = param_shardings(mesh, net)
    sparams = {k: jax.device_put(v, p_sh[k]) for k, v in params.items()}
    sbatch = shard_batch(mesh, batch)
    l1, g1 = jax.jit(jax.value_and_grad(loss_mesh))(sparams, sbatch)

    assert np.allclose(float(l0), float(l1), rtol=1e-5), (src, dst)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g0[k]), np.asarray(g1[k]),
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"{src}->{dst} {k}")


def test_net_level_default_applies_to_layers():
    """NetProto.partition_type is the default for layers without an
    explicit one (neuralnet.cc:45-56)."""
    cfg = _cfg("kNone", "kNone")
    cfg.neuralnet.partition_type = "kDataPartition"
    for l in cfg.neuralnet.layer:
        l.partition_type = None
    net = build_net(cfg, "kTrain", SHAPES)
    assert net.layer_partition("fc_src") == "kDataPartition"
    cfg.neuralnet.layer[3].partition_type = "kLayerPartition"
    net2 = build_net(cfg, "kTrain", SHAPES)
    assert net2.layer_partition("fc_src") == "kLayerPartition"
    assert net2.layer_partition("fc_dst") == "kDataPartition"


def test_indivisible_partition_shards_unevenly(capsys):
    """A dim that doesn't divide the mesh axis still partitions — GSPMD
    tiles with an implicit pad on the last shard, the compiler-native
    form of the reference handing the remainder to the last partition
    (neuralnet.cc:160-162, base_layer.cc:125-129).  A 30-wide layer on
    model=4 must (a) emit per-shard compute at width ceil(30/4)=8,
    (b) match the unpartitioned numerics, (c) not warn."""
    mesh = make_mesh(jax.devices(), data=2, model=4)
    cfg = _cfg("kNone", "kNone")
    cfg.neuralnet.layer[3].inner_product_param.num_output = 30
    cfg.neuralnet.layer[3].partition_type = "kLayerPartition"
    net = build_net(cfg, "kTrain", SHAPES)
    params = net.init_params(jax.random.PRNGKey(0))
    batch = _batch(np.random.default_rng(1))

    def loss_mesh(p, b):
        return net.apply(p, b, train=True, mesh=mesh)[0]

    l0, g0 = jax.jit(jax.value_and_grad(
        lambda p, b: net.apply(p, b, train=True)[0]))(params, batch)
    jitted = jax.jit(jax.value_and_grad(loss_mesh))
    l1, g1 = jitted(params, batch)
    assert np.allclose(float(l0), float(l1), rtol=1e-5)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g0[k]), np.asarray(g1[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)
    # per-shard width 8 appears in the SPMD-partitioned program
    hlo = jitted.lower(params, batch).compile().as_text()
    assert "f32[8,8]" in hlo or "f32[4,8]" in hlo, \
        "no ceil(30/4)-wide per-shard compute found in partitioned HLO"
    assert "not divisible" not in capsys.readouterr().err


def test_size10_param_partitions_on_model4_matches_unsharded():
    """The verdict's flagship case: a 10-wide classifier under
    kLayerPartition on model=4 (LeNet ip2) partitions its compute
    (storage stays replicated — device_put cannot tile 10 by 4) and a
    FULL sharded train step reproduces unsharded numerics."""
    from singa_tpu.core.trainer import Trainer

    mesh = make_mesh(jax.devices(), data=2, model=4)
    cfg = _cfg("kNone", "kLayerPartition")
    cfg.neuralnet.layer[5].inner_product_param.num_output = 10
    tr_flat = Trainer(cfg, SHAPES, donate=False)
    tr_mesh = Trainer(cfg, SHAPES, donate=False, mesh=mesh)
    params, opt = tr_flat.init(0)
    batch = _batch(np.random.default_rng(3))
    rng = jax.random.PRNGKey(0)
    p0, o0, m0 = tr_flat.train_step(params, opt, batch, 0, rng)

    p_sh = param_shardings(mesh, tr_mesh.train_net)
    sp = {k: jax.device_put(v, p_sh[k]) for k, v in params.items()}
    so = {k: {n: jax.device_put(v, p_sh[n]) for n, v in t.items()}
          for k, t in opt.items()}
    sb = shard_batch(mesh, batch)
    p1, o1, m1 = tr_mesh.train_step(sp, so, sb, 0, rng)
    assert float(m0["loss"]) == pytest.approx(float(m1["loss"]), rel=1e-5)
    for k in p0:
        np.testing.assert_allclose(np.asarray(p0[k]), np.asarray(p1[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)
    # the 10-wide fc_dst weight runs partition-constrained: ceil(10/4)=3
    hlo = tr_mesh.train_step.lower(sp, so, sb, 0, rng).compile().as_text()
    assert "3]" in hlo and "dynamic-slice" in hlo


def test_indivisible_batch_partition_matches_unpartitioned():
    """kDataPartition on a batch that doesn't divide the data axis
    (6 over data=2... and 10 over 4-wide model meshes): GSPMD's
    implicit pad must not change numerics."""
    mesh = make_mesh(jax.devices(), data=4, model=2)
    cfg = _cfg("kDataPartition", "kDataPartition")
    cfg.neuralnet.layer[0].data_param.batchsize = 6
    net = build_net(cfg, "kTrain", SHAPES)
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(9)
    batch = {"data": {
        "pixel": jnp.asarray(rng.standard_normal((6, 16)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (6,)))}}
    l0, g0 = jax.jit(jax.value_and_grad(
        lambda p, b: net.apply(p, b, train=True)[0]))(params, batch)
    l1, g1 = jax.jit(jax.value_and_grad(
        lambda p, b: net.apply(p, b, train=True, mesh=mesh)[0]))(
            params, batch)
    assert np.allclose(float(l0), float(l1), rtol=1e-5)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g0[k]), np.asarray(g1[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)


def test_uneven_param_storage_shards_with_padding():
    """Round-5 close of the storage gap: shard_params pads the 10-wide
    fc_dst weight to 12 and SHARDS it over model=4 (3 columns per
    device instead of a replicated 10), optimizer state follows, and a
    full sharded train step still reproduces unsharded numerics."""
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.parallel import shard_opt_state, shard_params

    mesh = make_mesh(jax.devices(), data=2, model=4)
    cfg = _cfg("kNone", "kLayerPartition")
    cfg.neuralnet.layer[5].inner_product_param.num_output = 10
    tr_flat = Trainer(cfg, SHAPES, donate=False)
    tr_mesh = Trainer(cfg, SHAPES, donate=False, mesh=mesh)
    params, opt = tr_flat.init(0)
    batch = _batch(np.random.default_rng(3))
    rng = jax.random.PRNGKey(0)
    p0, o0, m0 = tr_flat.train_step(params, opt, batch, 0, rng)

    sp = shard_params(mesh, tr_mesh.train_net, params)
    so = shard_opt_state(mesh, tr_mesh.train_net, opt)
    # find the fc_dst weight: logical (·, 10), stored (·, 12) sharded
    wname = [n for n, s in tr_mesh.train_net.param_specs.items()
             if s.shape[-1] == 10 and len(s.shape) == 2][0]
    assert sp[wname].shape[-1] == 12
    shard_shapes = {tuple(s.data.shape)
                    for s in sp[wname].addressable_shards}
    assert all(sh[-1] == 3 for sh in shard_shapes), shard_shapes
    # optimizer state shards identically
    for tree in so.values():
        if wname in tree:
            assert tree[wname].shape[-1] == 12
            assert all(tuple(s.data.shape)[-1] == 3
                       for s in tree[wname].addressable_shards)

    sb = shard_batch(mesh, batch)
    p1, o1, m1 = tr_mesh.train_step(sp, so, sb, 0, rng)
    assert float(m0["loss"]) == pytest.approx(float(m1["loss"]), rel=1e-5)
    for k in p0:
        a1 = np.asarray(p1[k])
        a0 = np.asarray(p0[k])
        if a1.shape != a0.shape:        # padded param: compare the body,
            sl = tuple(slice(0, d) for d in a0.shape)   # pad stays zero
            np.testing.assert_allclose(
                a1[tuple(slice(d, None) if i == len(a0.shape) - 1 else
                         slice(None) for i, d in enumerate(a0.shape))],
                0.0, atol=1e-7, err_msg=f"{k}: pad region moved")
            a1 = a1[sl]
        np.testing.assert_allclose(a0, a1, rtol=1e-4, atol=1e-6,
                                   err_msg=k)


def test_padded_storage_checkpoints_stay_spec_shaped():
    """Checkpoints must stay mesh-portable: the save boundary slices
    padded params AND optimizer state back to spec shapes
    (Trainer._ckpt_state), and pad_params is idempotent so re-sharding
    an already-padded tree cannot grow it again."""
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.parallel import pad_params, shard_opt_state, \
        shard_params

    mesh = make_mesh(jax.devices(), data=2, model=4)
    cfg = _cfg("kNone", "kLayerPartition")
    cfg.neuralnet.layer[5].inner_product_param.num_output = 10
    tr = Trainer(cfg, SHAPES, donate=False, mesh=mesh)
    params, opt = tr.init(0)
    sp = shard_params(mesh, tr.train_net, params)
    so = shard_opt_state(mesh, tr.train_net, opt)
    wname = [n for n, s in tr.train_net.param_specs.items()
             if s.shape[-1] == 10 and len(s.shape) == 2][0]
    assert sp[wname].shape[-1] == 12
    # idempotent: a second pad pass must not grow 12 -> 14
    again = pad_params(mesh, tr.train_net, sp)
    assert again[wname].shape[-1] == 12
    # the save boundary emits spec shapes for params and opt state
    cp, co = tr._ckpt_state(sp, so)
    for name, spec in tr.train_net.param_specs.items():
        assert tuple(cp[name].shape) == tuple(spec.shape), name
        for tree in co.values():
            assert tuple(tree[name].shape) == tuple(spec.shape), name


def test_resolve_params_rejects_config_mismatch():
    """_resolve_params only slices partition-dim pad; a checkpoint from
    a different config (wrong non-partition dim) must keep failing
    loudly instead of being silently truncated."""
    import jax.numpy as jnp

    from singa_tpu.core.net import build_net

    cfg = _cfg("kNone", "kLayerPartition")
    net = build_net(cfg, "kTrain", SHAPES)
    params = net.init_params(jax.random.PRNGKey(0))
    wname = [n for n, s in net.param_specs.items()
             if len(s.shape) == 2][0]
    spec = net.param_specs[wname]
    # grow the NON-partition dim: must NOT be sliced away
    bad = dict(params)
    bigger = tuple(d + 4 if i != spec.partition_dim else d
                   for i, d in enumerate(spec.shape))
    bad[wname] = jnp.zeros(bigger, jnp.float32)
    resolved = net._resolve_params(bad)
    assert tuple(resolved[wname].shape) == bigger  # untouched -> layer
    with pytest.raises(Exception):                 # fails loudly there
        net.apply(bad, _batch(np.random.default_rng(0)), train=False)


def test_resume_under_padded_mesh_roundtrips(tmp_path):
    """--resume with pad-to-divisible sharded storage: main.py resumes
    AFTER shard_params, so Trainer.resume receives a PADDED template
    while checkpoints are saved spec-shaped (_ckpt_state).  resume must
    unpad the template for the restore, then re-pad + re-shard under
    the trainer's mesh so the padded sharded layout survives."""
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.parallel import shard_opt_state, shard_params
    from singa_tpu.utils.checkpoint import CheckpointManager

    mesh = make_mesh(jax.devices(), data=2, model=4)
    cfg = _cfg("kNone", "kLayerPartition")
    cfg.neuralnet.layer[5].inner_product_param.num_output = 10
    tr = Trainer(cfg, SHAPES, donate=False, mesh=mesh)
    params, opt = tr.init(0)
    sp = shard_params(mesh, tr.train_net, params)
    so = shard_opt_state(mesh, tr.train_net, opt)
    CheckpointManager(str(tmp_path)).save(5, *tr._ckpt_state(sp, so))

    rp, ro, step = tr.resume(sp, so, str(tmp_path))
    assert step == 5
    wname = [n for n, s in tr.train_net.param_specs.items()
             if s.shape[-1] == 10 and len(s.shape) == 2][0]
    # restored storage is padded AND sharded again (3 columns/device)
    assert rp[wname].shape[-1] == 12
    assert all(tuple(s.data.shape)[-1] == 3
               for s in rp[wname].addressable_shards)
    for tree in ro.values():
        if wname in tree:
            assert tree[wname].shape[-1] == 12
    # values round-trip exactly (body of the padded arrays)
    for k, spec in tr.train_net.param_specs.items():
        body = np.asarray(rp[k])[tuple(slice(0, d) for d in spec.shape)]
        np.testing.assert_array_equal(body, np.asarray(params[k]), err_msg=k)


def test_unpad_params_keeps_non_partition_mismatch_loud():
    """unpad_params (the checkpoint save boundary) slices ONLY a
    partition-dim excess; an array oversized in a non-partition dim —
    a config mismatch — must pass through untouched so the save fails
    loudly downstream instead of writing a silently-cropped
    checkpoint."""
    import jax.numpy as jnp

    from singa_tpu.core.net import build_net

    cfg = _cfg("kNone", "kLayerPartition")
    net = build_net(cfg, "kTrain", SHAPES)
    params = net.init_params(jax.random.PRNGKey(0))
    wname = [n for n, s in net.param_specs.items()
             if len(s.shape) == 2][0]
    spec = net.param_specs[wname]
    bad = dict(params)
    bigger = tuple(d + 4 if i != spec.partition_dim else d
                   for i, d in enumerate(spec.shape))
    bad[wname] = jnp.zeros(bigger, jnp.float32)
    out = net.unpad_params(bad)
    assert tuple(out[wname].shape) == bigger       # NOT cropped
    # while a genuine partition-dim pad IS sliced off
    padded = dict(params)
    wider = tuple(d + 2 if i == spec.partition_dim else d
                  for i, d in enumerate(spec.shape))
    padded[wname] = jnp.zeros(wider, jnp.float32)
    assert tuple(net.unpad_params(padded)[wname].shape) \
        == tuple(spec.shape)
