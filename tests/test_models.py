"""Model zoo + checkpoint/resume + RBM tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.core.trainer import Trainer
from singa_tpu.models import (alexnet_cifar10, alexnet_imagenet, lenet_mnist,
                              mlp_mnist, rbm)
from singa_tpu.utils.checkpoint import CheckpointManager

CIFAR_SHAPES = {"data": {"pixel": (3, 32, 32), "label": ()}}
MNIST_SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def _cifar_batch(bs, seed=0):
    rng = np.random.default_rng(seed)
    return {"data": {
        "pixel": rng.integers(0, 256, (bs, 3, 32, 32)).astype(np.uint8),
        "label": rng.integers(0, 10, (bs,)).astype(np.int32)}}


def _mnist_batch(bs, seed=0):
    rng = np.random.default_rng(seed)
    return {"data": {
        "pixel": rng.integers(0, 256, (bs, 28, 28)).astype(np.uint8),
        "label": rng.integers(0, 10, (bs,)).astype(np.int32)}}


def test_alexnet_cifar10_builds_and_steps():
    cfg = alexnet_cifar10(batchsize=8, train_steps=2)
    trainer = Trainer(cfg, CIFAR_SHAPES, donate=False)
    net = trainer.train_net
    assert net.shapes["conv1"] == (8, 32, 32, 32)  # NHWC (h=w=c=32)
    assert net.shapes["pool1"] == (8, 16, 16, 32)
    assert net.shapes["pool3"] == (8, 4, 4, 64)
    assert net.shapes["ip1"] == (8, 10)
    params, opt = trainer.init(0)
    p, o, m = trainer.train_step(params, opt, _cifar_batch(8), 0,
                                 jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss"]))


def test_alexnet_imagenet_shapes():
    cfg = alexnet_imagenet(batchsize=2, nclass=100)
    shapes = {"data": {"pixel": (3, 256, 256), "label": ()}}
    trainer = Trainer(cfg, shapes, donate=False)
    net = trainer.train_net
    assert net.shapes["rgb"] == (2, 227, 227, 3)  # NHWC
    assert net.shapes["conv1"] == (2, 55, 55, 96)
    assert net.shapes["pool5"] == (2, 6, 6, 256)
    assert net.shapes["fc6"] == (2, 4096)
    assert net.shapes["fc8"] == (2, 100)


def test_programmatic_lenet_matches_conf_lenet():
    from singa_tpu.config import load_model_config
    from singa_tpu.core import build_net
    a = build_net(lenet_mnist(batchsize=4), "kTrain", MNIST_SHAPES)
    b = build_net(load_model_config(
        "/root/reference/examples/mnist/conv.conf"), "kTrain",
        MNIST_SHAPES, batchsize=4)
    for k in ("conv1", "pool1", "conv2", "pool2", "ip1", "ip2"):
        assert a.shapes[k] == b.shapes[k]


def test_checkpoint_roundtrip(tmp_path):
    cfg = lenet_mnist(batchsize=4, train_steps=2)
    trainer = Trainer(cfg, MNIST_SHAPES, donate=False)
    params, opt = trainer.init(0)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(7, params, opt)
    assert mgr.latest_step() == 7
    rp, ro, step = mgr.restore(template={"params": params, "opt_state": opt})
    assert step == 7
    np.testing.assert_allclose(np.asarray(rp["conv1/weight"]),
                               np.asarray(params["conv1/weight"]))
    np.testing.assert_allclose(
        np.asarray(ro["history"]["ip1/weight"]),
        np.asarray(opt["history"]["ip1/weight"]))


def test_trainer_checkpoint_and_resume(tmp_path):
    cfg = lenet_mnist(batchsize=4, train_steps=4)
    cfg.checkpoint_frequency = 2
    trainer = Trainer(cfg, MNIST_SHAPES, donate=False)
    params, opt = trainer.init(0)
    batches = iter(lambda: _mnist_batch(4), None)
    p2, o2, _ = trainer.run(params, opt, batches, workspace=str(tmp_path))
    rp, ro, step = trainer.resume(params, opt, str(tmp_path))
    assert step == 4
    np.testing.assert_allclose(np.asarray(rp["ip2/weight"]),
                               np.asarray(p2["ip2/weight"]))
    # resume from a fresh trainer continues without error
    p3, o3, _ = trainer.run(rp, ro, batches, start_step=step,
                            workspace=str(tmp_path))


def test_rbm_cd_learns_reconstruction():
    """CD-1 on a toy two-mode binary dataset must cut reconstruction
    error substantially."""
    rng = np.random.default_rng(0)
    modes = (rng.random((2, 16)) > 0.5).astype(np.float32)

    def data_factory():
        while True:
            idx = rng.integers(0, 2, 32)
            noise = rng.random((32, 16)) < 0.05
            yield jnp.asarray(np.logical_xor(modes[idx], noise)
                              .astype(np.float32))

    it = data_factory()
    params = rbm.init_rbm(jax.random.PRNGKey(0), 16, 8)
    _, recon0, _ = rbm.cd_grads(params, next(it), jax.random.PRNGKey(1))
    trained = rbm.pretrain_rbm(jax.random.PRNGKey(0), it, 16, 8,
                               steps=200, lr=0.1)
    _, recon1, _ = rbm.cd_grads(trained, next(it), jax.random.PRNGKey(2))
    assert float(recon1) < float(recon0) * 0.6, (float(recon0), float(recon1))


def test_rbm_greedy_stack_and_unroll():
    rng = np.random.default_rng(1)

    def data_factory():
        while True:
            yield jnp.asarray((rng.random((16, 20)) > 0.7).astype(np.float32))

    rbms = rbm.greedy_pretrain(jax.random.PRNGKey(0), data_factory,
                               widths=[12, 6], nvis=20, steps_per_layer=20,
                               log_fn=lambda s: None)
    assert rbms[0]["W"].shape == (20, 12)
    assert rbms[1]["W"].shape == (12, 6)
    params = rbm.unroll_autoencoder(rbms)
    v = jnp.asarray((rng.random((4, 20)) > 0.5).astype(np.float32))
    out = rbm.autoencoder_apply(params, v, nlayers=2)
    assert out.shape == (4, 20)
    # differentiable for fine-tuning
    g = jax.grad(lambda p: jnp.mean(
        (rbm.autoencoder_apply(p, v, 2) - v) ** 2))(params)
    assert np.isfinite(float(jnp.sum(jnp.abs(g["enc0/weight"]))))
