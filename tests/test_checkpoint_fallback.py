"""No-orbax checkpoint fallback hardening (ISSUE 1 satellite): atomic
tmp+rename writes, the checksummed manifest, and restore that walks
back past corrupt/partial snapshots to the previous good one.  Orbax is
forcibly disabled via monkeypatch so the numpy fallback is what runs —
the path a minimal deployment (or a CPU test box) actually exercises."""

import json
import os

import numpy as np
import pytest

from singa_tpu.utils import checkpoint as ckpt_mod
from singa_tpu.utils.checkpoint import CheckpointManager
from singa_tpu.utils.faults import FaultSchedule, FaultSpec, FaultError, \
    inject

pytestmark = pytest.mark.faults


@pytest.fixture
def no_orbax(monkeypatch):
    monkeypatch.setattr(ckpt_mod, "_HAVE_ORBAX", False)


def _state(v):
    return ({"w": np.full((4, 4), float(v), np.float32)},
            {"history": {"w": np.zeros((4, 4), np.float32)}})


def _mgr(tmp_path, logs=None):
    return CheckpointManager(str(tmp_path),
                             log_fn=(logs.append if logs is not None
                                     else (lambda s: None)))


def test_fallback_save_is_atomic_and_manifested(tmp_path, no_orbax):
    mgr = _mgr(tmp_path)
    mgr.save(1, *_state(1))
    mgr.save(2, *_state(2))
    names = sorted(os.listdir(mgr.dir))
    assert "step_1.npz" in names and "step_2.npz" in names
    assert not any(n.endswith(".tmp") for n in names)   # no torn leftovers
    man = json.load(open(os.path.join(mgr.dir, "MANIFEST.json")))
    assert set(man) == {"step_1.npz", "step_2.npz"}
    for name, entry in man.items():
        assert entry["size"] == os.path.getsize(
            os.path.join(mgr.dir, name))
        assert len(entry["sha256"]) == 64


def test_truncated_newest_falls_back_to_previous_good(tmp_path, no_orbax):
    """save → truncate the newest snapshot → restore returns the
    previous good checkpoint and logs the skip (the satellite's exact
    scenario)."""
    logs = []
    mgr = _mgr(tmp_path, logs)
    mgr.save(1, *_state(1))
    mgr.save(2, *_state(2))
    path2 = os.path.join(mgr.dir, "step_2.npz")
    with open(path2, "r+b") as f:
        f.truncate(os.path.getsize(path2) // 2)

    restored = _mgr(tmp_path, logs).restore()
    assert restored is not None
    params, opt, step = restored
    assert step == 1
    np.testing.assert_allclose(params["w"], 1.0)
    np.testing.assert_allclose(opt["history"]["w"], 0.0)
    assert any("corrupt or partial" in l and "step 2" in l for l in logs)


def test_bitflip_detected_by_manifest_checksum(tmp_path, no_orbax):
    """Same size, one flipped byte: only the sha256 catches it."""
    logs = []
    mgr = _mgr(tmp_path, logs)
    mgr.save(1, *_state(1))
    mgr.save(2, *_state(2))
    path2 = os.path.join(mgr.dir, "step_2.npz")
    size = os.path.getsize(path2)
    with open(path2, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    assert os.path.getsize(path2) == size

    restored = _mgr(tmp_path, logs).restore()
    assert restored is not None and restored[2] == 1


def test_pre_manifest_snapshot_still_restores(tmp_path, no_orbax):
    """Checkpoints written before the manifest existed (or whose
    manifest was lost) restore on load-verification alone."""
    mgr = _mgr(tmp_path)
    mgr.save(3, *_state(3))
    os.remove(os.path.join(mgr.dir, "MANIFEST.json"))
    restored = _mgr(tmp_path).restore()
    assert restored is not None and restored[2] == 3


def test_all_snapshots_corrupt_returns_none(tmp_path, no_orbax):
    logs = []
    mgr = _mgr(tmp_path, logs)
    mgr.save(1, *_state(1))
    for name in os.listdir(mgr.dir):
        if name.endswith(".npz"):
            p = os.path.join(mgr.dir, name)
            with open(p, "r+b") as f:
                f.truncate(4)
    assert _mgr(tmp_path, logs).restore() is None
    assert any("no restorable checkpoint" in l for l in logs)


def test_explicit_step_restore_walks_back(tmp_path, no_orbax):
    mgr = _mgr(tmp_path)
    mgr.save(1, *_state(1))
    mgr.save(2, *_state(2))
    mgr.save(3, *_state(3))
    path2 = os.path.join(mgr.dir, "step_2.npz")
    with open(path2, "r+b") as f:
        f.truncate(8)
    # asking for the corrupt step 2 lands on 1, never forward on 3
    restored = _mgr(tmp_path).restore(step=2)
    assert restored is not None and restored[2] == 1


def test_torn_fault_kind_simulates_lost_pages(tmp_path, no_orbax):
    """The `torn` fault kind at ckpt.save: the save call returns
    success but the snapshot on disk is garbage — restore must land on
    the previous save."""
    mgr = _mgr(tmp_path)
    with inject(FaultSchedule([FaultSpec("ckpt.save", 1, "torn")])):
        mgr.save(1, *_state(1))
        mgr.save(2, *_state(2))      # visit 1: torn on disk
    restored = _mgr(tmp_path).restore()
    assert restored is not None and restored[2] == 1


def test_error_fault_during_save_preserves_previous(tmp_path, no_orbax):
    """A crash at the start of a save (kind `error`) leaves the
    directory exactly as it was: the previous snapshot restores."""
    mgr = _mgr(tmp_path)
    mgr.save(1, *_state(1))
    with inject(FaultSchedule([FaultSpec("ckpt.save", 1, "error")])):
        mgr.save(1, *_state(1))      # visit 0 passes (re-save)
        with pytest.raises(FaultError):
            mgr.save(2, *_state(2))  # visit 1 crashes before any write
    assert not os.path.exists(os.path.join(mgr.dir, "step_2.npz"))
    restored = _mgr(tmp_path).restore()
    assert restored is not None and restored[2] == 1
