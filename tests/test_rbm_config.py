"""kRBM from the config surface: alg kContrastiveDivergence drives CD
pretraining through Trainer (VERDICT r1 item 7; model.proto:40-44)."""

import jax
import numpy as np

from singa_tpu.config import load_model_config, model_config_to_text
from singa_tpu.core.net import build_net
from singa_tpu.core.trainer import Trainer
from singa_tpu.data.synthetic import synthetic_image_batches
from singa_tpu.models.rbm import rbm_mnist

SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def test_krbm_layer_registers_and_forwards():
    cfg = rbm_mnist(widths=(32, 16), batchsize=8, train_steps=10)
    net = build_net(cfg, "kTrain", SHAPES)
    assert net.shapes["rbm0"] == (8, 32)
    assert net.shapes["rbm1"] == (8, 16)
    params = net.init_params(jax.random.PRNGKey(0))
    assert params["rbm0/weight"].shape == (784, 32)
    batch = next(synthetic_image_batches(8, seed=3, stream_seed=30))
    _, _, outs = net.apply(params, batch, train=False)
    h = np.asarray(outs["rbm1"])
    assert h.shape == (8, 16) and (h >= 0).all() and (h <= 1).all()


def test_conf_roundtrip_drives_cd_training(tmp_path):
    """Dump the rbm config to a text .conf, reload it, and train: the
    alg field routes Trainer.run into greedy CD, reconstruction error
    falls, and both RBMs get trained (greedy phase switch)."""
    path = tmp_path / "rbm.conf"
    path.write_text(model_config_to_text(
        rbm_mnist(widths=(32, 16), batchsize=32, train_steps=120,
                  lr=0.1)))
    cfg = load_model_config(str(path))
    assert cfg.alg == "kContrastiveDivergence"
    cfg.display_frequency = 20

    logs = []
    tr = Trainer(cfg, SHAPES, log_fn=logs.append, donate=False)
    params, opt = tr.init(seed=0)
    w0_before = np.asarray(params["rbm0/weight"]).copy()
    w1_before = np.asarray(params["rbm1/weight"]).copy()
    it = synthetic_image_batches(32, seed=3, stream_seed=30)
    params, opt, history = tr.run(params, opt, it, seed=0)

    recons = [h["recon"] for h in history]
    # phase 1 (rbm0) reconstruction improves within its budget
    assert recons[1] < recons[0]
    assert any("cd[rbm0]" in l for l in logs)
    assert any("cd[rbm1]" in l for l in logs)
    assert np.abs(np.asarray(params["rbm0/weight"]) - w0_before).max() > 0
    assert np.abs(np.asarray(params["rbm1/weight"]) - w1_before).max() > 0


def test_persistent_cd_runs_pcd_chain():
    """rbm_param.persistent=true continues the Gibbs chain across steps
    (PCD) — verified by observing the chain carried in Trainer.run_cd
    and that training still reduces reconstruction error."""
    cfg = rbm_mnist(widths=(32,), batchsize=16, train_steps=60, lr=0.1)
    cfg.neuralnet.layer[2].rbm_param.persistent = True
    cfg.display_frequency = 20
    tr = Trainer(cfg, SHAPES, log_fn=lambda s: None, donate=False)
    params, opt = tr.init(seed=0)
    it = synthetic_image_batches(16, seed=3, stream_seed=30)
    params, opt, history = tr.run(params, opt, it, seed=0)
    recons = [h["recon"] for h in history]
    assert recons[-1] < recons[0]


def test_cd_checkpoints_at_cadence(tmp_path):
    cfg = rbm_mnist(widths=(16,), batchsize=8, train_steps=20, lr=0.1)
    cfg.checkpoint_frequency = 10
    tr = Trainer(cfg, SHAPES, log_fn=lambda s: None, donate=False)
    params, opt = tr.init(seed=0)
    it = synthetic_image_batches(8, seed=3, stream_seed=30)
    tr.run(params, opt, it, seed=0, workspace=str(tmp_path))
    from singa_tpu.utils.checkpoint import CheckpointManager
    assert CheckpointManager(str(tmp_path)).latest_step() == 20
