"""Benchmark: MNIST LeNet (examples/mnist/conv.conf, identical to the
reference's conv.conf) training throughput on the available accelerator.

Prints ONE JSON line on stdout: {"metric", "value", "unit",
"vs_baseline"}.  Secondary metrics (AlexNet/CIFAR-10 MFU — north-star
gate 2 — and transformer-LM MFU) go to stderr so the driver contract
stays a single stdout line.

The reference publishes no numbers (README.md:1-5); BASELINE.md records
its harness only.  `vs_baseline` is computed against REFERENCE_IMG_SEC,
an estimate of the reference's single-node CPU throughput for the same
conv.conf workload (batch 64, im2col+BLAS LeNet at ~1k img/s — the
scale its 2015-era CPU cluster sweep targeted).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REFERENCE_IMG_SEC = 1000.0
BATCH = 512
ITERS = 50


def _time_steps(trainer, params, opt_state, batch, key, iters):
    # NOTE: sync via host fetch (hard_sync), NOT jax.block_until_ready —
    # the tunneled axon platform can return from block_until_ready before
    # execution finishes, which yields impossible (>100% MFU) timings.
    # Per-dispatch tunnel overhead is ~1ms, comparable to a small-model
    # step, so all `iters` steps run as ONE compiled lax.scan program
    # (trainer.train_steps) — device-only inner loop, one dispatch.
    from singa_tpu.utils.profiler import hard_sync
    # warmup = one full scan call: compiles the nsteps program and runs it
    params, opt_state, _ = trainer.train_steps(
        params, opt_state, batch, 0, key, iters)
    hard_sync(params)
    t0 = time.perf_counter()
    params, opt_state, _ = trainer.train_steps(
        params, opt_state, batch, iters, key, iters)
    hard_sync(params)
    return (time.perf_counter() - t0) / iters


def bench_lenet():
    import jax

    from singa_tpu.config import load_model_config
    from singa_tpu.core.trainer import Trainer

    cfg = load_model_config(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "examples/mnist/conv.conf"))
    for layer in cfg.neuralnet.layer:
        if layer.data_param:
            layer.data_param.batchsize = BATCH
    shapes = {"data": {"pixel": (28, 28), "label": ()}}
    trainer = Trainer(cfg, shapes, log_fn=lambda s: None)
    params, opt_state = trainer.init(seed=0)

    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jax.device_put(
            rng.integers(0, 256, (BATCH, 28, 28)).astype(np.uint8)),
        "label": jax.device_put(
            rng.integers(0, 10, (BATCH,)).astype(np.int32)),
    }}
    step_s = _time_steps(trainer, params, opt_state, batch,
                         jax.random.PRNGKey(0), ITERS)
    img_sec = BATCH / step_s
    print(json.dumps({
        "metric": "mnist_lenet_train_throughput",
        "value": round(img_sec, 1),
        "unit": "img/sec/chip",
        "vs_baseline": round(img_sec / REFERENCE_IMG_SEC, 2),
    }))


def bench_alexnet_mfu(batch_size=2048, precision="bfloat16"):
    """North-star gate 2: AlexNet/CIFAR-10 at >=50% MFU (BASELINE.md).

    Measured on the actual 5-conv AlexNet stack adapted to 32x32
    (models.vision.alexnet_cifar10_full); the 3-conv caffe quick net is
    reported alongside as cifar10_quick (its 32-channel convs cap the
    128-lane MXU well below the gate regardless of software quality).
    """
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.vision import alexnet_cifar10, alexnet_cifar10_full
    from singa_tpu.utils.flops import mfu, net_train_flops

    shapes = {"data": {"pixel": (3, 32, 32), "label": ()}}
    rng = np.random.default_rng(0)
    for metric, cfg, bs, iters in (
            ("alexnet_cifar10_mfu", alexnet_cifar10_full(batchsize=batch_size),
             batch_size, 20),
            ("cifar10_quick_mfu", alexnet_cifar10(batchsize=batch_size),
             batch_size, ITERS)):
        cfg.precision = precision
        trainer = Trainer(cfg, shapes, log_fn=lambda s: None)
        params, opt_state = trainer.init(seed=0)
        batch = {"data": {
            "pixel": jax.device_put(
                rng.standard_normal((bs, 3, 32, 32)).astype(np.float32)),
            "label": jax.device_put(
                rng.integers(0, 10, (bs,)).astype(np.int32)),
        }}
        step_s = _time_steps(trainer, params, opt_state, batch,
                             jax.random.PRNGKey(0), iters)
        flops = net_train_flops(trainer.train_net)
        util = mfu(flops, step_s)
        print(json.dumps({
            "metric": metric, "value":
                round(util, 4) if util is not None else None,
            "unit": "fraction_of_peak", "img_sec": round(bs / step_s, 1),
            "step_ms": round(step_s * 1e3, 3), "model_tflops_per_step":
                round(flops / 1e12, 4), "precision": precision,
        }), file=sys.stderr)


def bench_transformer_mfu(batch_size=8, seq_len=1024, precision="bfloat16"):
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)
    from singa_tpu.utils.flops import compiled_flops, mfu

    cfg = transformer_lm(vocab_size=32768, num_layers=12, embed_dim=768,
                         num_heads=12, head_dim=64, seq_len=seq_len,
                         batchsize=batch_size)
    cfg.precision = precision
    trainer = Trainer(cfg, {"data": {"input": (seq_len,),
                                     "target": (seq_len,)}},
                      log_fn=lambda s: None)
    params, opt_state = trainer.init(seed=0)
    batch = next(synthetic_token_batches(batch_size, seq_len, 32768))
    batch = jax.tree_util.tree_map(jax.device_put, batch)
    key = jax.random.PRNGKey(0)
    step_s = _time_steps(trainer, params, opt_state, batch, key,
                         ITERS)
    flops = compiled_flops(trainer.train_step, params, opt_state, batch,
                           0, key)
    util = mfu(flops, step_s) if flops else None
    ntok = batch_size * seq_len
    print(json.dumps({
        "metric": "transformer_lm_mfu", "value":
            round(util, 4) if util is not None else None,
        "unit": "fraction_of_peak", "tok_sec": round(ntok / step_s, 1),
        "step_ms": round(step_s * 1e3, 3), "precision": precision,
    }), file=sys.stderr)


def main() -> None:
    bench_lenet()
    if "--extra" in sys.argv:
        for fn in (bench_alexnet_mfu, bench_transformer_mfu):
            try:
                fn()
            except Exception as e:  # secondary metrics must not break the
                print(json.dumps({"metric": fn.__name__,  # driver contract
                                  "error": repr(e)}), file=sys.stderr)


if __name__ == "__main__":
    main()
