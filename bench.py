"""Benchmark: MNIST LeNet (reference examples/mnist/conv.conf) training
throughput on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (README.md:1-5); BASELINE.md records
its harness only.  `vs_baseline` is computed against REFERENCE_IMG_SEC,
an estimate of the reference's single-node CPU throughput for the same
conv.conf workload (batch 64, im2col+BLAS LeNet at ~1k img/s — the
scale its 2015-era CPU cluster sweep targeted).
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_IMG_SEC = 1000.0
BATCH = 512
WARMUP = 3
ITERS = 20


def main() -> None:
    import jax

    from singa_tpu.config import load_model_config
    from singa_tpu.core.trainer import Trainer

    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    for layer in cfg.neuralnet.layer:
        if layer.data_param:
            layer.data_param.batchsize = BATCH
    shapes = {"data": {"pixel": (28, 28), "label": ()}}
    trainer = Trainer(cfg, shapes, log_fn=lambda s: None)
    params, opt_state = trainer.init(seed=0)

    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jax.device_put(
            rng.integers(0, 256, (BATCH, 28, 28)).astype(np.uint8)),
        "label": jax.device_put(
            rng.integers(0, 10, (BATCH,)).astype(np.int32)),
    }}
    key = jax.random.PRNGKey(0)

    for step in range(WARMUP):
        params, opt_state, metrics = trainer.train_step(
            params, opt_state, batch, step, key)
    jax.block_until_ready(params)

    t0 = time.perf_counter()
    for step in range(WARMUP, WARMUP + ITERS):
        params, opt_state, metrics = trainer.train_step(
            params, opt_state, batch, step, key)
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0

    img_sec = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "mnist_lenet_train_throughput",
        "value": round(img_sec, 1),
        "unit": "img/sec/chip",
        "vs_baseline": round(img_sec / REFERENCE_IMG_SEC, 2),
    }))


if __name__ == "__main__":
    main()
