"""Benchmark driver.  Prints ONE JSON line on stdout.

The stdout metric is the north-star gate 2 (BASELINE.md): CIFAR-10
AlexNet MFU on the 5-conv `alexnet_cifar10_full` stack, measured on
the available accelerator at the throughput-optimal batch size.
`vs_baseline` is value / 0.50 — the fraction of the >=50%-MFU gate —
because the reference publishes no numbers of its own (README.md:1-5;
BASELINE.md records its harness only).

Secondary metrics go to stderr so the driver contract stays a single
stdout line:
  * mnist_lenet_train_throughput — img/s/chip for the reference's own
    examples/mnist/conv.conf (batch enlarged to fill the chip), with
    vs_baseline grounded against REFERENCE_CPU_IMG_SEC: the SAME
    conv.conf workload measured through this framework's CPU backend
    on this host (single process, matching the reference's
    single-node CPU worker; measured 2026-07-30, best window
    4.7 ms/step at batch 64 => ~13.6k img/s).  Re-measure with
    `JAX_PLATFORMS=cpu python bench.py --cpu-baseline`.
  * cifar10_quick_mfu — the 3-conv caffe 'quick' net (its 32-channel
    convs cap the 128-lane MXU well below the gate regardless of
    software quality).
  * transformer_lm_mfu — the transformer LM stack.
  * mnist time-to-99%: produced by tools/convergence_run.py (a full
    training run, too slow for every bench invocation); if a committed
    CONVERGENCE.json exists its numbers are folded into the stdout
    line as aux keys.

Timing: ALL steps of a measurement run as ONE compiled lax.scan
program (trainer.train_steps) — device-only inner loop, one dispatch —
and sync is a host fetch (hard_sync), NOT jax.block_until_ready, which
can return early on the tunneled axon platform (observed impossible
>100% MFU).  Each metric reports the best of several scan windows
(run-to-run noise on the tunnel is ~±5%).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))

# Measured on this host — see module docstring and --cpu-baseline.
REFERENCE_CPU_IMG_SEC = 13600.0

GATE_MFU = 0.50


def _best_window(trainer, params, opt_state, batch, key, iters, reps):
    from singa_tpu.utils.profiler import hard_sync
    params, opt_state, _ = trainer.train_steps(
        params, opt_state, batch, 0, key, iters)
    hard_sync(params)
    best = float("inf")
    for r in range(reps):
        t0 = time.perf_counter()
        params, opt_state, _ = trainer.train_steps(
            params, opt_state, batch, iters, key, iters)
        hard_sync(params)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def _lenet_trainer(batch_size):
    import jax

    from singa_tpu.config import load_model_config
    from singa_tpu.core.trainer import Trainer

    cfg = load_model_config(os.path.join(REPO, "examples/mnist/conv.conf"))
    for layer in cfg.neuralnet.layer:
        if layer.data_param:
            layer.data_param.batchsize = batch_size
    trainer = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                      log_fn=lambda s: None)
    params, opt_state = trainer.init(seed=0)
    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jax.device_put(
            rng.integers(0, 256, (batch_size, 28, 28)).astype(np.uint8)),
        "label": jax.device_put(
            rng.integers(0, 10, (batch_size,)).astype(np.int32)),
    }}
    return trainer, params, opt_state, batch


def _cifar_mfu(cfg, batch_size, iters, reps, precision):
    """Shared CIFAR measurement: build trainer, synthetic batch, best
    scan window, analytic train MFU."""
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.utils.flops import mfu, net_train_flops

    cfg.precision = precision
    trainer = Trainer(cfg, {"data": {"pixel": (3, 32, 32), "label": ()}},
                      log_fn=lambda s: None)
    params, opt_state = trainer.init(seed=0)
    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jax.device_put(
            rng.standard_normal((batch_size, 3, 32, 32)).astype(np.float32)),
        "label": jax.device_put(
            rng.integers(0, 10, (batch_size,)).astype(np.int32)),
    }}
    step_s = _best_window(trainer, params, opt_state, batch,
                          jax.random.PRNGKey(0), iters, reps)
    flops = net_train_flops(trainer.train_net)
    return mfu(flops, step_s), step_s, flops


def bench_alexnet_mfu(batch_size=8192, iters=50, reps=4,
                      precision="bfloat16"):
    """North-star gate 2 (the judged stdout metric).

    iters=50: the per-dispatch tunnel overhead (~30ms per train_steps
    call) amortizes to noise at 50 steps per compiled window —
    measured 126.8 ms/step at iters=10 vs 123.7 at iters=50 on the
    same chip state; steady-state training runs the same fused scan
    (Trainer.run scan_chunk), so the longer window is the honest
    steady-state number."""
    from singa_tpu.models.vision import alexnet_cifar10_full

    util, step_s, flops = _cifar_mfu(alexnet_cifar10_full(
        batchsize=batch_size), batch_size, iters, reps, precision)
    return {
        "metric": "alexnet_cifar10_mfu",
        "value": round(util, 4) if util is not None else None,
        "unit": "fraction_of_peak",
        "vs_baseline": (round(util / GATE_MFU, 4)
                        if util is not None else None),
        "img_sec": round(batch_size / step_s, 1),
        "step_ms": round(step_s * 1e3, 3),
        "batch": batch_size,
        "model_tflops_per_step": round(flops / 1e12, 4),
        "precision": precision,
    }


def bench_lenet(batch_size=512, iters=50, reps=3):
    import jax

    trainer, params, opt_state, batch = _lenet_trainer(batch_size)
    step_s = _best_window(trainer, params, opt_state, batch,
                          jax.random.PRNGKey(0), iters, reps)
    img_sec = batch_size / step_s
    return {
        "metric": "mnist_lenet_train_throughput",
        "value": round(img_sec, 1),
        "unit": "img/sec/chip",
        "vs_baseline": round(img_sec / REFERENCE_CPU_IMG_SEC, 2),
        "baseline_img_sec_cpu": REFERENCE_CPU_IMG_SEC,
    }


def bench_cpu_baseline(iters=20, reps=5):
    """Measure REFERENCE_CPU_IMG_SEC on this host: the reference's own
    conv.conf (batch 64) through the CPU backend, single process.
    Run with JAX_PLATFORMS=cpu; refuses to record an accelerator
    number as a CPU baseline."""
    import jax

    if jax.default_backend() != "cpu":
        raise SystemExit("--cpu-baseline must run on the CPU backend: "
                         "JAX_PLATFORMS=cpu python bench.py --cpu-baseline "
                         f"(got {jax.default_backend()!r})")
    trainer, params, opt_state, batch = _lenet_trainer(64)
    step_s = _best_window(trainer, params, opt_state, batch,
                          jax.random.PRNGKey(0), iters, reps)
    print(json.dumps({"metric": "lenet_cpu_baseline",
                      "value": round(64 / step_s, 1),
                      "unit": "img/sec", "step_ms":
                          round(step_s * 1e3, 3)}))


def bench_quick_mfu(batch_size=2048, iters=50, reps=3,
                    precision="bfloat16"):
    from singa_tpu.models.vision import alexnet_cifar10

    util, step_s, _ = _cifar_mfu(alexnet_cifar10(batchsize=batch_size),
                                 batch_size, iters, reps, precision)
    return {"metric": "cifar10_quick_mfu",
            "value": round(util, 4) if util is not None else None,
            "unit": "fraction_of_peak",
            "img_sec": round(batch_size / step_s, 1)}


def bench_transformer_mfu(batch_size=32, seq_len=1024, iters=30,
                          precision="bfloat16", head_dim=64):
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)
    from singa_tpu.utils.flops import mfu, net_train_flops

    cfg = transformer_lm(vocab_size=32768, num_layers=12, embed_dim=768,
                         num_heads=768 // head_dim, head_dim=head_dim,
                         seq_len=seq_len,
                         batchsize=batch_size)
    cfg.precision = precision
    trainer = Trainer(cfg, {"data": {"input": (seq_len,),
                                     "target": (seq_len,)}},
                      log_fn=lambda s: None)
    params, opt_state = trainer.init(seed=0)
    batch = next(synthetic_token_batches(batch_size, seq_len, 32768))
    batch = jax.tree_util.tree_map(jax.device_put, batch)
    key = jax.random.PRNGKey(0)
    step_s = _best_window(trainer, params, opt_state, batch, key, iters, 3)
    # analytic model flops: XLA's cost analysis cannot see inside the
    # Pallas flash custom calls, so compiled_flops under-counts the
    # attention terms (~30% of this stack)
    flops = net_train_flops(trainer.train_net)
    util = mfu(flops, step_s)
    return {"metric": "transformer_lm_mfu",
            "value": round(util, 4) if util is not None else None,
            "unit": "fraction_of_peak",
            "tok_sec": round(batch_size * seq_len / step_s, 1),
            "step_ms": round(step_s * 1e3, 3),
            "model_tflops_per_step": round(flops / 1e12, 4)}


def bench_decode(batch_size=8, prompt_len=128, new_tokens=256,
                 reps=3, precision="bfloat16"):
    """KV-cache decode throughput: tokens/sec across the batch for the
    bench transformer (12L 768E 32k vocab), greedy sampling, one
    compiled prefill+scan program (models/generate.py).  vs_baseline is
    null — there is no reference decode path to compare against (the
    reference is train/test only); the row exists to make inference
    regressions visible round over round (BASELINE.md "Decode
    path")."""
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.generate import generate
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.utils.profiler import hard_sync

    seq = prompt_len + new_tokens
    cfg = transformer_lm(vocab_size=32768, num_layers=12, embed_dim=768,
                         num_heads=12, head_dim=64, seq_len=seq,
                         batchsize=batch_size)
    cfg.precision = precision
    trainer = Trainer(cfg, {"data": {"input": (seq,), "target": (seq,)}},
                      log_fn=lambda s: None)
    net = trainer.test_net or trainer.train_net
    params, _ = trainer.init(seed=0)
    if precision == "bfloat16":
        import jax.numpy as jnp
        params = {k: v.astype(jnp.bfloat16) for k, v in params.items()}
    rng = np.random.default_rng(0)
    prompt = jax.device_put(rng.integers(
        0, 32768, (batch_size, prompt_len)).astype(np.int32))

    def timed(n_new):
        # max_len pins the cache geometry to the full run's, so the
        # 1-new-token prefill probe runs the IDENTICAL prefill program
        # (same cache allocation, same masked-dense score width) and
        # the subtraction isolates exactly the decode steps
        out = generate(net, params, prompt, n_new,
                       max_len=seq)                  # compile + warm
        hard_sync(out)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = generate(net, params, prompt, n_new, max_len=seq)
            hard_sync(out)
            best = min(best, time.perf_counter() - t0)
        return best

    # prefill isolated via a 1-new-token run so the per-decode-step
    # number tracks the decode path only (a prefill-only speedup must
    # not move the decode regression anchor)
    t_full, t_prefill = timed(new_tokens), timed(1)
    decode_s = max(t_full - t_prefill, 1e-9) / (new_tokens - 1)
    tok_sec = batch_size / decode_s
    return {"metric": "decode_tok_sec",
            "value": round(tok_sec, 1),
            "unit": "tokens/sec/chip",
            "vs_baseline": None,
            "batch": batch_size, "prompt_len": prompt_len,
            "new_tokens": new_tokens,
            "ms_per_decode_step": round(decode_s * 1e3, 3),
            "prefill_ms": round(t_prefill * 1e3, 3),
            "end_to_end_tok_sec": round(
                batch_size * new_tokens / t_full, 1),
            "precision": precision}


def bench_feed_smoke(batch_size=64, steps=60, scan_chunk=10,
                     out=None):
    """Feed-pipeline A/B (ISSUE 2 acceptance): the LeNet train loop
    through Trainer.run with the DeviceFeeder ON vs OFF at the same
    scan_chunk, pulling the synthetic source DIRECTLY (no Prefetcher:
    that is a separate batch-granular stage — this smoke isolates the
    feed stage, so the off leg pays generation + stacking inline
    exactly where a prefetch-less loop would).  Reports steps/sec and
    the HOST-WAIT FRACTION of loop wall time: (wait + inline stage)
    for the synchronous leg vs consumer-side wait alone for the
    overlapped leg, whose staging runs on the producer thread.  `out`
    writes the JSON line to a file as well (scripts/perf_smoke.sh ->
    BENCH_pr2.json).

    batch 64 (not the throughput-optimal 512): this container's CPU is
    a single core, so the A/B must keep the compute share small enough
    that the data path is measurable at all; the fraction, not the
    absolute throughput, is the recorded metric."""
    import jax

    from singa_tpu.data.synthetic import synthetic_image_batches

    trainer, _, _, _ = _lenet_trainer(batch_size)
    trainer.cfg.train_steps = steps
    trainer.cfg.display_frequency = 0
    trainer.cfg.test_frequency = 0

    def one(feeder):
        params, opt_state = trainer.init(seed=0)
        it = synthetic_image_batches(batch_size, seed=1, stream_seed=7)
        trainer.timer.reset()
        t0 = time.perf_counter()
        trainer.run(params, opt_state, it, seed=0,
                    scan_chunk=scan_chunk, feeder=feeder)
        wall = time.perf_counter() - t0
        tm = dict(trainer.timer.times)
        host_wait = tm.get("wait", 0.0) + (0.0 if feeder
                                           else tm.get("stage", 0.0))
        return {"wall_s": round(wall, 4),
                "steps_per_sec": round(steps / wall, 2),
                "img_per_sec": round(steps * batch_size / wall, 1),
                "wait_s": round(tm.get("wait", 0.0), 4),
                "stage_s": round(tm.get("stage", 0.0), 4),
                "train_s": round(tm.get("train", 0.0), 4),
                "host_wait_fraction": round(host_wait / wall, 4)}

    one(False)   # warm the compile caches so both A/B legs are steady
    off, on = one(False), one(True)
    result = {
        "metric": "lenet_feed_pipeline",
        "value": round(off["host_wait_fraction"]
                       - on["host_wait_fraction"], 4),
        "unit": "host_wait_fraction_drop",
        "feeder_on": on, "feeder_off": off,
        "batch": batch_size, "steps": steps, "scan_chunk": scan_chunk,
        "backend": jax.default_backend(),
        "cpu_count": os.cpu_count(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_serve_smoke(n_clients=6, reqs_per_client=5, out=None):
    """Serving-tier smoke (ISSUE 5 acceptance): N concurrent clients
    sustain traffic against the HTTP frontend on CPU, and the run
    FAILS (raises) unless:
      * zero program compiles after warmup (the compiled-bucket
        contract — every request padded into an AOT executable);
      * a mid-run checkpoint hot-reload lands with zero dropped or
        failed in-flight requests;
      * an injected `serve.reload` fault degrades to serving the OLD
        params (counted in ServeStats.reload_failures, params_step
        unmoved, process up) and the next clean poll recovers.
    Records p50/p95 latency, occupancy, and QPS; `out` writes the JSON
    line to a file as well (scripts/serve_smoke.sh -> BENCH_pr5.json).
    The model is bench-tiny (2L 32E vocab 64): the subject under test
    is the serving machinery, not the matmuls."""
    import json as _json
    import tempfile
    import threading
    import urllib.request

    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import InferenceEngine, InferenceServer, ServeSpec
    from singa_tpu.utils.checkpoint import CheckpointManager
    from singa_tpu.utils.faults import FaultSchedule, inject

    vocab, seq = 64, 16
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    opt = {"t": np.zeros(())}

    ws = tempfile.mkdtemp(prefix="serve_smoke_")
    mgr = CheckpointManager(ws, max_to_keep=10, log_fn=lambda s: None)
    mgr.save(1, params, opt, health={"verdict": "ok"})

    spec = ServeSpec(buckets=((2, 8), (4, 8), (4, 16)),
                     max_new_tokens=8, batch_window_s=0.01,
                     request_timeout_s=30.0, reload_poll_s=100.0)
    engine = InferenceEngine(net, spec, workspace=ws,
                             log_fn=lambda s: None)
    engine.load()
    warm = engine.warmup()

    server = InferenceServer(engine, port=0, log_fn=lambda s: None)
    server.start()
    host, port = server.address
    url = f"http://{host}:{port}"

    def post(path, payload):
        req = urllib.request.Request(
            f"{url}{path}", data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return _json.loads(r.read())

    errors, results = [], []
    rng = np.random.default_rng(0)
    prompts = [[rng.integers(1, vocab, rng.integers(1, 13)).tolist()
                for _ in range(reqs_per_client)]
               for _ in range(n_clients)]

    def client(i):
        try:
            for p in prompts[i]:
                results.append(post("/generate", {"tokens": p}))
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(repr(e))

    # mid-run hot reload: clients in flight while the params swap
    p2 = jax.tree_util.tree_map(lambda a: a * 1.01, params)
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    mgr.save(2, p2, opt, health={"verdict": "ok"})
    r1 = engine.poll_reload()
    # injected reload fault mid-traffic: must degrade, not crash
    mgr.save(3, params, opt, health={"verdict": "ok"})
    with inject(FaultSchedule.parse("serve.reload@0:error")):
        r2 = engine.poll_reload()
    step_after_fault = engine.params_step
    r3 = engine.poll_reload()   # clean poll recovers
    for t in threads:
        t.join()

    # read the final stats through the HTTP endpoint — the same surface
    # an operator scrapes
    with urllib.request.urlopen(f"{url}/stats", timeout=10) as r:
        snap = _json.loads(r.read())
    server.stop()

    n_total = n_clients * reqs_per_client
    failures = []
    if errors:
        failures.append(f"client errors: {errors}")
    if len(results) != n_total or snap["completed"] < n_total:
        failures.append(f"dropped requests: {len(results)}/{n_total} "
                        f"responses, {snap['completed']} completed")
    if snap["failed"] or snap["expired"]:
        failures.append(f"failed={snap['failed']} "
                        f"expired={snap['expired']}")
    if snap["compiles"] != warm:
        failures.append(f"recompiled after warmup: {snap['compiles']} "
                        f"!= {warm}")
    if r1 != "reloaded":
        failures.append(f"mid-run hot reload did not land: {r1}")
    if r2 != "failed" or step_after_fault != 2:
        failures.append(f"reload fault did not degrade to old params: "
                        f"{r2}, step {step_after_fault}")
    if snap["reload_failures"] != 1:
        failures.append(f"reload failure not counted: "
                        f"{snap['reload_failures']}")
    if r3 != "reloaded" or snap["params_step"] != 3:
        failures.append(f"post-fault recovery failed: {r3}, "
                        f"step {snap['params_step']}")
    if failures:
        raise RuntimeError("serve smoke FAILED: " + "; ".join(failures))

    result = {
        "metric": "serve_smoke_p50_latency",
        "value": snap["p50_latency_ms"],
        "unit": "ms",
        "p95_latency_ms": snap["p95_latency_ms"],
        "batch_occupancy": snap["batch_occupancy"],
        "qps": snap["qps"],
        "requests": n_total,
        "clients": n_clients,
        "batches": snap["batches"],
        "compiles_warmup": warm,
        "compiles_total": snap["compiles"],
        "reloads": snap["reloads"],
        "reload_failures": snap["reload_failures"],
        "served_step": snap["params_step"],
        "buckets": [list(b) for b in spec.buckets],
        "backend": __import__("jax").default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_fleet_smoke(n_clients=6, reqs_per_client=6, out=None):
    """Fleet smoke (ISSUE 7 acceptance): a 3-engine fleet behind the
    router + FleetServer sustains concurrent HTTP traffic on CPU, and
    the run FAILS (raises) unless:
      * killing 1 of 3 engines mid-load costs ZERO client-visible
        failures — every request either retries onto a healthy sibling
        or sheds with 503 + Retry-After (clients honor it); never a
        500, never a hang.  The dead engine is quarantined and, once
        revived, readmitted (kill->readmission time is recorded);
      * a DIVERGED checkpoint save is canaried on exactly one engine
        and auto-rolled back — at no point do >=2 engines serve the
        bad fingerprint, and the fleet ends on the old step;
      * a healthy save afterwards promotes fleet-wide (every engine on
        the new step).
    Records fleet p50/p95, kill-recovery time, and rollout outcome
    counts; `out` writes the JSON line to a file as well
    (scripts/fleet_smoke.sh -> BENCH_pr7.json)."""
    import json as _json
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import (EngineFleet, FleetServer, RolloutSpec,
                                 RouterSpec, ServeSpec)
    from singa_tpu.utils.checkpoint import CheckpointManager

    vocab, seq = 64, 16
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    opt = {"t": np.zeros(())}

    ws = tempfile.mkdtemp(prefix="fleet_smoke_")
    mgr = CheckpointManager(ws, max_to_keep=10, log_fn=lambda s: None)
    mgr.save(1, params, opt, health={"verdict": "ok"})

    spec = ServeSpec(buckets=((2, 8), (4, 16)), max_new_tokens=6,
                     batch_window_s=0.005, request_timeout_s=30.0)
    fleet = EngineFleet.local(
        net, spec, 3, workspace=ws, params=params,
        router_spec=RouterSpec(probe_period_s=0.05,
                               quarantine_after=1,
                               readmit_base_s=0.05, readmit_cap_s=0.5),
        rollout_spec=RolloutSpec(poll_s=0.05, window_s=0.2),
        log_fn=lambda s: None)
    fleet.start()
    front = FleetServer(fleet, port=0, log_fn=lambda s: None)
    front.start()
    host, port = front.address
    url = f"http://{host}:{port}"

    errors, results = [], []
    sheds = [0]
    stop_traffic = threading.Event()

    def post_with_retry(payload):
        # the well-behaved client: honor 503 + Retry-After, treat any
        # other 5xx (or a hang) as a real failure
        for _ in range(50):
            req = urllib.request.Request(
                f"{url}/generate", data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    return _json.loads(r.read())
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    sheds[0] += 1
                    time.sleep(float(
                        e.headers.get("Retry-After", 0.05)) or 0.05)
                    continue
                raise
        raise RuntimeError("request still shed after 50 retries")

    rng = np.random.default_rng(0)
    prompts = [[rng.integers(1, vocab, rng.integers(1, 13)).tolist()
                for _ in range(reqs_per_client)]
               for _ in range(n_clients)]

    def client(i):
        try:
            for p in prompts[i]:
                results.append(post_with_retry({"tokens": p}))
                if stop_traffic.is_set():
                    return
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(repr(e))

    # -- phase 1: kill one engine under load, measure recovery --------
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    time.sleep(0.1)                  # let traffic land on every engine
    victim = fleet.router.healthy_names()[0]
    handle = fleet.router.handle_for(victim)
    t_kill = time.perf_counter()
    handle.kill()
    time.sleep(0.3)
    handle.revive()
    deadline = time.time() + 15
    while time.time() < deadline and \
            fleet.router.stats.readmissions == 0:
        time.sleep(0.02)
    kill_recovery_s = time.perf_counter() - t_kill
    for t in threads:
        t.join()

    # -- phase 2: diverged canary -> rollback, healthy -> promote -----
    def engine_steps():
        return [fleet.router.handle_for(n).engine.params_step
                for n in fleet.router.names()]

    probe = np.arange(1, 6, dtype=np.int32).tolist()
    max_on_bad = [0]
    mgr.save(2, params, opt, health={"verdict": "diverged"})
    deadline = time.time() + 20
    while time.time() < deadline and fleet.rollout.rollbacks == 0:
        max_on_bad[0] = max(max_on_bad[0],
                            sum(1 for s in engine_steps() if s == 2))
        post_with_retry({"tokens": probe})
    steps_after_rollback = engine_steps()
    mgr.save(3, params, opt, health={"verdict": "ok"})
    deadline = time.time() + 20
    while time.time() < deadline and fleet.rollout.promotions == 0:
        post_with_retry({"tokens": probe})
    time.sleep(0.1)
    steps_after_promote = engine_steps()

    with urllib.request.urlopen(f"{url}/stats", timeout=10) as r:
        snap = _json.loads(r.read())
    ro = fleet.rollout.snapshot()
    front.stop()
    fleet.stop()

    n_total = n_clients * reqs_per_client
    failures = []
    if errors:
        failures.append(f"client-visible failures: {errors}")
    if len(results) != n_total:
        failures.append(f"dropped requests: {len(results)}/{n_total}")
    if snap["quarantines"] < 1:
        failures.append("killed engine was never quarantined")
    if snap["readmissions"] < 1:
        failures.append("revived engine was never readmitted")
    if ro["rollbacks"] != 1 or max_on_bad[0] > 1:
        failures.append(f"diverged rollout not contained: rollbacks="
                        f"{ro['rollbacks']}, engines on bad "
                        f"fingerprint={max_on_bad[0]}")
    if steps_after_rollback != [1, 1, 1]:
        failures.append(f"fleet not restored to pinned step after "
                        f"rollback: {steps_after_rollback}")
    if ro["promotions"] != 1 or steps_after_promote != [3, 3, 3]:
        failures.append(f"healthy rollout did not promote fleet-wide: "
                        f"promotions={ro['promotions']}, steps "
                        f"{steps_after_promote}")
    if failures:
        raise RuntimeError("fleet smoke FAILED: " + "; ".join(failures))

    result = {
        "metric": "fleet_smoke_p50_latency",
        "value": snap["p50_latency_ms"],
        "unit": "ms",
        "p95_latency_ms": snap["p95_latency_ms"],
        "kill_recovery_s": round(kill_recovery_s, 3),
        "engines": 3,
        "clients": n_clients,
        "requests": n_total,
        "routed": snap["routed"],
        "completed": snap["completed"],
        "retried": snap["retried"],
        "shed_http_503": sheds[0],
        "quarantines": snap["quarantines"],
        "readmissions": snap["readmissions"],
        "canaries": ro["canaries"],
        "promotions": ro["promotions"],
        "rollbacks": ro["rollbacks"],
        "refusals": ro["refusals"],
        "final_steps": steps_after_promote,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_pipeline_smoke(out=None):
    """ISSUE 10 acceptance: the closed train-and-serve loop on CPU,
    twice over one tiny LM — the run FAILS (raises) unless:

    Clean phase: a throttled supervised trainer publishes 4 blessed
    checkpoints (steps 6/12/18/24); EVERY one of them is canaried and
    promoted, in order, with zero rollbacks, and the blessed-to-served
    lag stays single-digit seconds.

    Faulted phase (fresh workspace): under seeded injection — a
    trainer preemption (kill), a torn checkpoint save (corrupt), and a
    NaN'd gradient window (diverge) — zero client requests fail, no
    response ever comes from below the promoted step or from a
    non-blessed step, the torn save is refused at the canary, and the
    loop still drains (served == last blessed) by the end.

    Records both phases' counters; `out` writes the JSON line to a
    file as well (scripts/pipeline_smoke.sh -> BENCH_pr10.json)."""
    import tempfile
    import threading

    import jax

    from singa_tpu.core.pipeline import PipelineController, PipelineSpec
    from singa_tpu.core.supervisor import Supervisor
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)
    from singa_tpu.serve import EngineFleet, RolloutSpec, ServeSpec
    from singa_tpu.utils.faults import FaultSchedule, inject
    from singa_tpu.utils.health import HealthMonitor, HealthSpec

    vocab, seq = 64, 16
    shapes = {"data": {"input": (seq,), "target": (seq,)}}
    blessed_cadence = (6, 12, 18, 24)

    def run_loop(schedule):
        """One closed-loop run; returns (controller, supervisor,
        fleet, responses[(pinned_before, step)], failures,
        pinned_transitions)."""
        cfg = transformer_lm(vocab_size=vocab, num_layers=2,
                             embed_dim=32, num_heads=4, head_dim=8,
                             seq_len=seq, batchsize=4, train_steps=24)
        cfg.checkpoint_frequency = 6
        ws = tempfile.mkdtemp(prefix="pipeline_smoke_")
        tr = Trainer(cfg, shapes, log_fn=lambda s: None, donate=False,
                     health=HealthMonitor(HealthSpec(),
                                          log_fn=lambda s: None))
        sup = Supervisor(tr, ws, max_restarts=3, log=lambda s: None)
        net = tr.test_net or tr.train_net
        fleet = EngineFleet.local(
            net, ServeSpec(buckets=((2, 8),), max_new_tokens=4,
                           batch_window_s=0.002),
            2, workspace=ws,
            params=net.init_params(jax.random.PRNGKey(0)),
            rollout_spec=RolloutSpec(poll_s=0.05, window_s=0.2,
                                     min_requests=1),
            log_fn=lambda s: None)
        ctl = PipelineController(sup, fleet, ws,
                                 spec=PipelineSpec(lag_alarm_s=30),
                                 log_fn=lambda s: None)
        # pace training (~0.2 s/step) so the rollout can promote every
        # cadence save before the next one lands — the clean phase
        # gates on promote-per-publish, not newest-wins catch-up
        throttle = [lambda s, m: time.sleep(0.2)]
        rng = np.random.default_rng(0)
        responses, transitions, failures = [], [], [0]
        with inject(schedule):
            ctl.start(lambda: synthetic_token_batches(4, seq, vocab,
                                                      seed=5),
                      seed=0, hooks=throttle)
            try:
                deadline = time.monotonic() + 300.0
                while time.monotonic() < deadline:
                    done = not ctl.train_running()
                    lag = ctl.lag()
                    pinned = fleet.rollout.pinned_step
                    if not transitions or transitions[-1] != pinned:
                        transitions.append(pinned)
                    plen = int(rng.integers(1, 7))
                    prompt = rng.integers(1, vocab,
                                          plen).astype(np.int32)
                    try:
                        got = ctl.generate(prompt)
                        responses.append((pinned, got["step"]))
                    except Exception:  # noqa: BLE001 — gated below
                        failures[0] += 1
                    if done and lag["lag_steps"] == 0 and \
                            lag["blessed_step"] >= 0:
                        break
                if not ctl.wait(timeout=60.0):
                    raise RuntimeError("pipeline training never "
                                       "finished")
            finally:
                ctl.stop()
        return ctl, sup, fleet, responses, failures[0], transitions

    failures = []

    # -- clean phase: every blessed checkpoint promotes, in order -----
    ctl, sup, fleet, responses, client_failures, transitions = \
        run_loop(None)
    clean_lag = ctl.lag()
    promoted = [p for p in transitions if p >= 0]
    if ctl.train_error is not None or sup.failures:
        failures.append(f"clean run not clean: {ctl.train_error!r}, "
                        f"{sup.failures}")
    if client_failures:
        failures.append(f"clean run client failures: "
                        f"{client_failures}")
    if promoted != list(blessed_cadence):
        failures.append(f"clean run did not promote every blessed "
                        f"checkpoint in order: {promoted} != "
                        f"{list(blessed_cadence)}")
    if fleet.rollout.rollbacks != 0:
        failures.append(f"clean run rolled back "
                        f"{fleet.rollout.rollbacks}x")
    clean_promote_lag = (max(ctl.promote_lags_s)
                         if ctl.promote_lags_s else None)
    if clean_promote_lag is None or clean_promote_lag >= 10.0:
        failures.append(f"blessed-to-served lag not single-digit "
                        f"seconds: {clean_promote_lag}")
    clean = {
        "published": ctl.published,
        "promotions": fleet.rollout.promotions,
        "rollbacks": fleet.rollout.rollbacks,
        "canary_restarts": fleet.rollout.canary_restarts,
        "promoted_sequence": promoted,
        "promote_lag_max_s": (round(clean_promote_lag, 3)
                              if clean_promote_lag else None),
        "requests": len(responses),
        "client_failures": client_failures,
        "served_step": clean_lag["served_step"],
    }

    # -- faulted phase: kill + corrupt + diverge, traffic never blinks
    sched = FaultSchedule.parse(
        "step.train@8:preempt,ckpt.save@2:torn,step.grad@14:nan",
        seed=0)
    ctl, sup, fleet, responses, client_failures, transitions = \
        run_loop(sched)
    fault_lag = ctl.lag()
    blessed_ok = set(blessed_cadence) | {-1}
    below_pinned = [(p, s) for p, s in responses if s < p]
    off_blessed = sorted({s for _, s in responses}) if any(
        s not in blessed_ok for _, s in responses) else []
    if client_failures:
        failures.append(f"faulted run client failures: "
                        f"{client_failures}")
    if ctl.train_error is not None:
        failures.append(f"faulted run training failed: "
                        f"{ctl.train_error!r}")
    kinds = sorted(f.kind for f in sup.failures)
    if kinds != ["divergence", "preemption"]:
        failures.append(f"expected one preemption + one divergence "
                        f"rescue, got {kinds}")
    if {f.site for f in sched.fired} != \
            {"step.train", "ckpt.save", "step.grad"}:
        failures.append(f"injected faults did not all fire: "
                        f"{sched.fired}")
    if below_pinned:
        failures.append(f"responses served from below the promoted "
                        f"step: {below_pinned[:5]}")
    if off_blessed:
        failures.append(f"responses served from non-blessed steps: "
                        f"{off_blessed}")
    if fleet.rollout.refusals < 1:
        failures.append("torn checkpoint was never refused at the "
                        "canary")
    if fault_lag["lag_steps"] != 0 or \
            fault_lag["served_step"] != blessed_cadence[-1]:
        failures.append(f"faulted loop did not drain: {fault_lag}")
    faulted = {
        "published": ctl.published,
        "promotions": fleet.rollout.promotions,
        "rollbacks": fleet.rollout.rollbacks,
        "refusals": fleet.rollout.refusals,
        "torn_polls": fleet.rollout.mgr.torn_polls,
        "supervisor_failures": kinds,
        "requests": len(responses),
        "client_failures": client_failures,
        "served_step": fault_lag["served_step"],
        "blessed_step": fault_lag["blessed_step"],
    }

    if failures:
        raise RuntimeError("pipeline smoke FAILED: "
                           + "; ".join(failures))

    result = {
        "metric": "pipeline_smoke_promote_lag",
        "value": clean["promote_lag_max_s"],
        "unit": "s",
        "clean": clean,
        "faulted": faulted,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_cb_smoke(n_requests=64, n_long=3, out=None):
    """ISSUE 8 acceptance: continuous batching vs the static bucket
    path under the same mixed load, over real HTTP.  61 shorts
    (max_new=2) + 3 longs (max_new=256) hit each server; the run
    FAILS (raises) unless:
      * on the cb leg, at least one short request that was submitted
        AFTER a long generation produced its first streamed token
        completes BEFORE that long generation finishes (no
        head-of-line blocking);
      * cb p95 <= 0.5x static p95 (shorts no longer pay for the
        batch-mate's full 256-token decode);
      * both legs compile O(1) programs at warmup and ZERO after
        (static: one bucket program; cb: one prefill + one decode).
    Records p50/p95/p99, decode tok/s, slot occupancy, block-pool
    utilization, and compile counts for both paths; `out` writes the
    JSON line as well (scripts/serve_smoke.sh -> BENCH_pr8.json).
    The model is bench-tiny: the subject is the scheduler, not the
    matmuls."""
    import json as _json
    import queue as _queue
    import threading
    import urllib.request

    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import InferenceEngine, InferenceServer, ServeSpec

    vocab, seq = 64, 16
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))

    n_short = n_requests - n_long
    # a 1024-token horizon puts the static path's pay-for-max cost in
    # real decode compute (a 2-token request still rides a 1024-step
    # scan), not per-call overhead — the regime the gate is about
    max_new_long = 1024
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, vocab, int(rng.integers(1, seq + 1)))
               .tolist() for _ in range(n_requests)]

    def run_leg(spec, streaming):
        engine = InferenceEngine(net, spec, params=params,
                                 log_fn=lambda s: None)
        warm = engine.warmup()
        server = InferenceServer(engine, port=0, log_fn=lambda s: None)
        server.start()
        host, port = server.address
        url = f"http://{host}:{port}"

        def post(payload, timeout=120):
            req = urllib.request.Request(
                f"{url}/generate", data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return _json.loads(r.read())

        errors, lat = [], [None] * n_requests
        long_first_tok = [None] * n_long   # monotonic, per long
        long_done = [None] * n_long
        short_span = [None] * n_short      # (t_submit, t_done)
        t_base = time.monotonic()

        def long_client(j):
            try:
                body = {"tokens": prompts[j], "timeout": 120,
                        "max_new": max_new_long}
                t0 = time.monotonic()
                if streaming:
                    body["stream"] = True
                    req = urllib.request.Request(
                        f"{url}/generate",
                        data=_json.dumps(body).encode(),
                        headers={"Content-Type": "application/json"})
                    ntok = 0
                    with urllib.request.urlopen(req, timeout=120) as r:
                        for ln in r:
                            if not ln.strip():
                                continue
                            ev = _json.loads(ln)
                            if "error" in ev and "done" not in ev:
                                raise RuntimeError(ev["error"])
                            if "token" in ev:
                                ntok += 1
                                if long_first_tok[j] is None:
                                    long_first_tok[j] = time.monotonic()
                            if ev.get("done"):
                                assert len(ev["tokens"]) == ntok
                else:
                    outp = post(body)
                    assert len(outp["tokens"]) == max_new_long
                    long_first_tok[j] = t0   # no stream: submit time
                long_done[j] = time.monotonic()
                lat[j] = long_done[j] - t0
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(f"long[{j}]: {e!r}")

        work: "_queue.Queue" = _queue.Queue()
        for i in range(n_short):
            work.put(i)

        def short_worker():
            while True:
                try:
                    i = work.get_nowait()
                except _queue.Empty:
                    return
                try:
                    t0 = time.monotonic()
                    outp = post({"tokens": prompts[n_long + i],
                                 "timeout": 120, "max_new": 2})
                    t1 = time.monotonic()
                    assert len(outp["tokens"]) == 2
                    lat[n_long + i] = t1 - t0
                    short_span[i] = (t0, t1)
                except Exception as e:  # noqa: BLE001
                    errors.append(f"short[{i}]: {e!r}")

        longs = [threading.Thread(target=long_client, args=(j,))
                 for j in range(n_long)]
        for t in longs:
            t.start()
        # shorts join a load that is already decoding the longs; 8
        # closed-loop workers keep the static queue several batches
        # deep without turning the cb leg's own admission drain into
        # the bottleneck
        time.sleep(0.05)
        workers = [threading.Thread(target=short_worker)
                   for _ in range(8)]
        for t in workers:
            t.start()
        for t in workers + longs:
            t.join()
        wall = time.monotonic() - t_base

        with urllib.request.urlopen(f"{url}/stats", timeout=10) as r:
            snap = _json.loads(r.read())
        server.stop()
        return {"errors": errors, "lat": lat, "snap": snap,
                "warm": warm, "wall": wall,
                "long_first_tok": long_first_tok,
                "long_done": long_done, "short_span": short_span}

    st_spec = ServeSpec(buckets=((2, seq),), max_new_tokens=max_new_long,
                        temperature=0.0, batch_window_s=0.005,
                        request_timeout_s=120.0, reload_poll_s=100.0)
    cb_spec = ServeSpec(buckets=((2, seq),), max_new_tokens=max_new_long,
                        temperature=0.0, request_timeout_s=120.0,
                        reload_poll_s=100.0,
                        cb="on", cb_slots=8, cb_block_len=4)
    st = run_leg(st_spec, streaming=False)
    cb = run_leg(cb_spec, streaming=True)

    def quantiles(lat):
        a = np.sort(np.asarray([v for v in lat if v is not None]))
        return {q: float(a[min(int(q / 100 * a.size), a.size - 1)])
                for q in (50, 95, 99)}

    failures = []
    for leg, name in ((st, "static"), (cb, "cb")):
        if leg["errors"]:
            failures.append(f"{name} client errors: {leg['errors']}")
        if any(v is None for v in leg["lat"]):
            failures.append(f"{name}: dropped requests")
        if leg["snap"]["compiles"] != leg["warm"]:
            failures.append(
                f"{name} recompiled after warmup: "
                f"{leg['snap']['compiles']} != {leg['warm']}")
    # the tentpole behavior: a short admitted after a long's first
    # streamed token finishes while that long is still decoding
    overlapped = any(
        ft is not None and dn is not None and sp is not None
        and sp[0] > ft and sp[1] < dn
        for ft, dn in zip(cb["long_first_tok"], cb["long_done"])
        for sp in cb["short_span"])
    if not overlapped:
        failures.append("no short request completed while a long "
                        "generation was still decoding")
    stq, cbq = quantiles(st["lat"]), quantiles(cb["lat"])
    if not failures and cbq[95] > 0.5 * stq[95]:
        failures.append(f"cb p95 {cbq[95] * 1e3:.1f}ms > 0.5x static "
                        f"p95 {stq[95] * 1e3:.1f}ms")
    if failures:
        raise RuntimeError("cb smoke FAILED: " + "; ".join(failures))

    result = {
        "metric": "cb_smoke_p95_ratio",
        "value": round(cbq[95] / stq[95], 4),
        "unit": "cb_p95_over_static_p95",
        "gate": 0.5,
        "requests": n_requests,
        "long_requests": n_long,
        "max_new_long": max_new_long,
        "short_completed_while_long_decoding": overlapped,
        "static": {
            "p50_ms": round(stq[50] * 1e3, 3),
            "p95_ms": round(stq[95] * 1e3, 3),
            "p99_ms": round(stq[99] * 1e3, 3),
            "wall_s": round(st["wall"], 3),
            "tokens_per_s_p50": st["snap"]["p50_tokens_per_s"],
            "generated_tokens": st["snap"]["generated_tokens"],
            "batch_occupancy": st["snap"]["batch_occupancy"],
            "compiles_warmup": st["warm"],
            "compiles_total": st["snap"]["compiles"],
        },
        "cb": {
            "p50_ms": round(cbq[50] * 1e3, 3),
            "p95_ms": round(cbq[95] * 1e3, 3),
            "p99_ms": round(cbq[99] * 1e3, 3),
            "wall_s": round(cb["wall"], 3),
            "tokens_per_s_p50": cb["snap"]["p50_tokens_per_s"],
            "generated_tokens": cb["snap"]["generated_tokens"],
            "slot_occupancy": cb["snap"]["cb_slot_occupancy"],
            "block_utilization": cb["snap"]["cb_block_utilization"],
            "decode_steps": cb["snap"]["cb_steps"],
            "slots": cb_spec.cb_slots,
            "block_len": cb_spec.cb_block_len,
            "pool_blocks": cb_spec.cb_pool_blocks,
            "compiles_warmup": cb["warm"],
            "compiles_total": cb["snap"]["compiles"],
        },
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_obs_overhead(batch_size=64, steps=96, scan_chunk=8,
                       reps=3, out=None):
    """ISSUE 6 acceptance: `--obs on` must cost < 3% wall time on the
    chunked LeNet training loop (the span-per-chunk hot path: one
    trainer.chunk + feeder.stage span pair per dispatch, plus the
    trace buffer append).  A/B of identical runs — obs off vs obs on
    with trace + event log under a temp dir — best-of-`reps` each leg
    to shave scheduler noise.  `value` is the overhead fraction
    (on/off - 1); `out` writes the JSON line as well
    (scripts/obs_smoke.sh -> BENCH_pr6.json)."""
    import tempfile

    import jax

    from singa_tpu import obs
    from singa_tpu.data.synthetic import synthetic_image_batches

    trainer, _, _, _ = _lenet_trainer(batch_size)
    trainer.cfg.train_steps = steps
    trainer.cfg.display_frequency = 0
    trainer.cfg.test_frequency = 0
    trainer.cfg.checkpoint_frequency = 0

    def one():
        params, opt_state = trainer.init(seed=0)
        it = synthetic_image_batches(batch_size, seed=1, stream_seed=7)
        t0 = time.perf_counter()
        trainer.run(params, opt_state, it, seed=0,
                    scan_chunk=scan_chunk)
        return time.perf_counter() - t0

    one()   # warm the compile caches so both legs are steady-state
    tmp = tempfile.mkdtemp(prefix="obs_bench_")
    spec = obs.ObsSpec(trace=os.path.join(tmp, "trace.json"),
                       events=os.path.join(tmp, "events.jsonl"))

    # interleaved A/B reps: host drift (thermal, allocator state)
    # hits both legs equally instead of biasing whichever ran last
    off = on = float("inf")
    for _ in range(reps):
        off = min(off, one())
        with obs.session(spec):
            on = min(on, one())
    overhead = on / off - 1.0
    result = {
        "metric": "obs_overhead",
        "value": round(overhead, 4),
        "unit": "wall_time_fraction",
        "gate": 0.03,
        "passed": overhead < 0.03,
        "wall_obs_off_s": round(off, 4),
        "wall_obs_on_s": round(on, 4),
        "batch": batch_size, "steps": steps, "scan_chunk": scan_chunk,
        "reps": reps,
        "backend": jax.default_backend(),
        "cpu_count": os.cpu_count(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_perf_smoke(n_requests=12, n_long=2, out=None):
    """ISSUE 15 acceptance: the performance observatory measured end
    to end.  One training leg (tiny MLP through the fused scan —
    readiness timer, train_scan compile accounting, analytic memory
    components) and one cb serving leg under mixed load (exactly 2
    warmup compiles, 0 after; readiness + HBM watermark exported in
    /metrics; CostWatch harvest adds 0 compiles), then the interleaved
    obs-overhead A/B (observatory collectors ride every session
    registry, so the PR 6 ≤3% bar re-certifies with perf on) and a
    `bench_report.py --trajectory` render over the existing
    artifacts.  Writes BENCH_pr15.json."""
    import json as _json
    import subprocess
    import threading
    import urllib.request

    import jax

    from singa_tpu.config.schema import model_config_from_dict
    from singa_tpu.core.net import build_net
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.data.synthetic import synthetic_image_batches
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.obs import perf
    from singa_tpu.obs.metrics import parse_prometheus
    from singa_tpu.serve import (InferenceEngine, InferenceServer,
                                 ServeSpec)

    perf.reset()

    # -- training leg: readiness latch + CompileWatch on the scan ----------
    tcfg = model_config_from_dict({
        "name": "perf_mlp", "train_steps": 8, "display_frequency": 0,
        "updater": {"type": "kSGD", "base_learning_rate": 0.1,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kShardData",
             "data_param": {"batchsize": 8}},
            {"name": "mnist", "type": "kMnistImage",
             "srclayers": "data"},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip", "type": "kInnerProduct",
             "srclayers": "mnist",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "weight"}, {"name": "bias"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip", "label"]}]}})
    trainer = Trainer(tcfg, {"data": {"pixel": (28, 28), "label": ()}},
                      donate=False, log_fn=lambda s: None)
    tp, to = trainer.init(0)
    it = synthetic_image_batches(8, seed=1, stream_seed=7)
    chunk = [next(it) for _ in range(4)]
    stacked = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *chunk)
    # the convergence tool's pre-compile path: CompileWatch times it,
    # CostWatch harvests it, and trainer.run below reuses the warm
    # executable
    trainer.compiled_scan(tp, to, stacked, 0, jax.random.PRNGKey(0),
                          4, True)
    trainer.run(tp, to, synthetic_image_batches(8, seed=1,
                                                stream_seed=7),
                seed=0, scan_chunk=4)
    tsnap = perf.snapshot()
    restart_training = tsnap["training_ready_s"] or 0.0
    train_compiles = tsnap["compiles"].get("train_scan", 0)

    # -- serving leg: tiny cb engine under mixed long/short load ----------
    vocab, seq = 64, 16
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    max_new_long = 64
    spec = ServeSpec(buckets=((2, seq),), max_new_tokens=max_new_long,
                     temperature=0.0, request_timeout_s=120.0,
                     reload_poll_s=100.0,
                     cb="on", cb_slots=8, cb_block_len=4)
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, port=0, log_fn=lambda s: None)
    server.start()                 # load + warmup (2 cb programs)
    warmup_compiles = engine.stats.compiles
    host, port = server.address
    url = f"http://{host}:{port}"
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, vocab, int(rng.integers(1, 13)))
               .tolist() for _ in range(n_requests)]
    errors, lat = [], []

    def post(tokens, max_new):
        t0 = time.monotonic()
        req = urllib.request.Request(
            f"{url}/generate",
            data=_json.dumps({"tokens": tokens, "timeout": 120,
                              "max_new": max_new}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            outp = _json.loads(r.read())
        assert len(outp["tokens"]) == max_new
        lat.append(time.monotonic() - t0)

    def client(i):
        try:
            post(prompts[i], max_new_long if i < n_long else 2)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(f"req[{i}]: {e!r}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    post_warmup = engine.stats.compiles - warmup_compiles

    # CostWatch no-recompile property: a full harvest sweep over the
    # compiled programs must not move the compile counter
    before = engine.stats.compiles
    harvested = engine.harvest_costs()
    costwatch_compiles = engine.stats.compiles - before

    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
        metrics = parse_prometheus(r.read().decode())
    server.stop()

    snap = perf.snapshot()
    restart_serving = metrics.get("singa_restart_to_serving_seconds",
                                  0.0)
    hbm_watermark = metrics.get("singa_hbm_watermark_bytes", 0.0)
    rss = metrics.get("singa_process_rss_bytes", 0.0)
    cb_flops = snap["cost"].get("cb_decode", {}).get("flops", 0.0)
    mfu = metrics.get('singa_program_mfu{program="cb_decode"}')

    # -- overhead A/B: the observatory's collectors are registered on
    # every obs session registry, so the PR 6 bar re-certifies here
    over = bench_obs_overhead(batch_size=16, steps=32, scan_chunk=8,
                              reps=2)

    # -- trajectory render over the existing artifacts (run before
    # this bench's own artifact lands, so a previously-green tree
    # stays the reference) --
    traj = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "bench_report.py"),
         "--trajectory", REPO],
        capture_output=True, text=True)

    def gate(value, bound, op):
        ok = {"==": value == bound, "<=": value <= bound,
              ">": value > bound}[op]
        return {"value": value, "bound": bound, "op": op, "pass": ok}

    gates = {
        "warmup_cb_compiles": gate(warmup_compiles, 2, "=="),
        "post_warmup_compiles": gate(post_warmup, 0, "=="),
        "recompile_anomalies": gate(snap["anomalies"], 0, "=="),
        "restart_to_serving": gate(round(restart_serving, 4), 0, ">"),
        "restart_to_training": gate(round(restart_training, 4), 0,
                                    ">"),
        "hbm_watermark": gate(hbm_watermark, 0, ">"),
        "costwatch_compiles": gate(costwatch_compiles, 0, "=="),
        "obs_overhead": gate(over["value"], 0.03, "<="),
        "trajectory_renders": gate(traj.returncode, 0, "=="),
    }
    failures = [f"gate {k}: {g['value']} not {g['op']} {g['bound']}"
                for k, g in gates.items() if not g["pass"]]
    if errors:
        failures.append(f"client errors: {errors}")
    if rss <= 0:
        failures.append("process collector missing from /metrics")
    if harvested < 2 or cb_flops <= 0:
        failures.append(f"CostWatch harvested nothing "
                        f"({harvested} programs, flops {cb_flops})")
    if traj.returncode != 0:
        failures.append(f"trajectory: {traj.stderr.strip()[-500:]}")
    if failures:
        raise RuntimeError("perf smoke FAILED: " + "; ".join(failures))

    a = np.sort(np.asarray(lat))
    result = {
        "metric": "perf_smoke_post_warmup_compiles",
        "value": post_warmup,
        "unit": "compiles",
        "restart_to_serving_s": round(restart_serving, 4),
        "restart_to_training_s": round(restart_training, 4),
        "hbm_watermark_bytes": int(hbm_watermark),
        "memory_components": snap["memory_components"],
        "obs_overhead": over["value"],
        "compile_seconds_sum": snap["compile_seconds_sum"],
        "compiles": snap["compiles"],
        "train_scan_compiles": train_compiles,
        "cost_programs": sorted(snap["cost"]),
        "cb_decode_flops": cb_flops,
        "mfu_cb_decode": mfu,       # None on CPU (peak table has no
                                    # entry); populated on TPU
        "short_p95_ms": round(float(
            a[min(int(0.95 * a.size), a.size - 1)]) * 1e3, 3),
        "requests": n_requests,
        "gates": gates,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def _convergence_aux():
    path = os.path.join(REPO, "CONVERGENCE.json")
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            d = json.load(f)
        out = {}
        for k in ("mnist_test_accuracy", "time_to_99_seconds",
                  "steps_to_99"):
            if k in d:
                out[k] = d[k]
        return out
    except Exception:
        return {}


def bench_traffic_smoke(out=None):
    """ISSUE 11 acceptance: the SLO-driven autoscaler under adversarial
    open-loop traffic on CPU — a 1-engine fleet rides a ramp -> flash
    crowd -> decay -> quiet schedule and the run FAILS (raises) unless:
      * the fleet GREW under the flash crowd (scale_ups >= 1, peak
        engine count above the starting size) and SHRANK back once
        quiet (scale_downs >= 1, final count below peak) — capacity
        followed the workload in both directions;
      * p95 stayed inside the SLO outside the spike (gated on the
        quiet phase: the steady state the autoscaler converged to);
      * zero non-shed failures and zero harness drops — every offered
        request completed or was shed with Overloaded, nothing else;
      * retiring the engine that holds a live slow-reader stream with
        drain=True delivers EVERY token and the done event before the
        member leaves — scale-down never drops an in-flight stream.
    Records per-phase offered/completed/shed and percentiles, the
    autoscaler outcome counters, and the engine-count trajectory;
    `out` writes the JSON line to a file as well
    (scripts/traffic_smoke.sh -> BENCH_pr11.json)."""
    import tempfile
    import threading

    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import (EngineFleet, RolloutSpec, RouterSpec,
                                 ServeSpec)
    from singa_tpu.serve.autoscale import AutoScaler, AutoScaleSpec
    from singa_tpu.serve.traffic import (TrafficGen, flash_crowd, ramp,
                                         steady)
    from singa_tpu.utils.checkpoint import CheckpointManager

    vocab, seq = 64, 16
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))

    ws = tempfile.mkdtemp(prefix="traffic_smoke_")
    mgr = CheckpointManager(ws, log_fn=lambda s: None)
    mgr.save(1, params, {"t": np.zeros(())}, health={"verdict": "ok"})

    # 2 slots + a 4-deep queue caps one engine well under the flash
    # rate: the ramp fits, the flash does not — the spike has to be
    # answered with capacity, not absorbed
    spec = ServeSpec(buckets=((2, 16),), max_new_tokens=48,
                     batch_window_s=0.002, request_timeout_s=30.0,
                     queue_capacity=4, cb="on", cb_slots=2,
                     cb_block_len=8)
    ascale = AutoScaleSpec(slo_p95_ms=1000.0, max_shed_rate=0.02,
                           min_engines=1, max_engines=3,
                           cooldown_s=1.0, window_s=1.5, tick_s=0.1,
                           quiet_ticks=10, queue_high=4.0,
                           occ_high=0.9, drain_timeout_s=20.0)
    fleet = EngineFleet.local(
        net, spec, 1, workspace=ws, params=params,
        router_spec=RouterSpec(probe_period_s=0.05,
                               quarantine_after=3),
        rollout_spec=RolloutSpec(poll_s=0.2, window_s=0.5),
        log_fn=lambda s: None)
    fleet.start()
    scaler = AutoScaler(fleet, spec=ascale, log_fn=lambda s: None)
    scaler.start()

    # engine-count trajectory, sampled while traffic runs
    sizes = []
    stop_sampling = threading.Event()

    def sample():
        while not stop_sampling.wait(0.05):
            sizes.append(len([m for m in fleet.router.members()
                              if not m.get("draining")]))

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()

    gen = TrafficGen(
        lambda toks: fleet.generate(toks.tolist()),
        stream_fn=lambda toks, max_new=None: fleet.generate_stream(
            toks.tolist(), max_new=max_new),
        vocab=vocab, seed=0, log_fn=lambda s: None)
    phases = [ramp("ramp", 4.0, 2.0, 6.0, prompt_lens=(4, 8)),
              flash_crowd("flash", 5.0, 6.0, k=20.0,
                          prompt_lens=(4, 8)),
              ramp("decay", 4.0, 6.0, 2.0, prompt_lens=(4, 8)),
              steady("quiet", 6.0, 1.0, prompt_lens=(4,))]
    rep = gen.run(phases, drain_timeout_s=30.0)

    # idle tail: give the quiet-streak hysteresis room to scale down
    deadline = time.time() + 20
    while time.time() < deadline and scaler.scale_downs == 0:
        time.sleep(0.1)
    time.sleep(0.3)                      # let a draining member leave
    stop_sampling.set()
    sampler.join(2.0)
    scaler.stop()

    # -- drain sub-test: retire the engine holding a live stream ------
    while len(fleet.router.names()) < 2:
        fleet.grow()
    probe = np.arange(1, 5, dtype=np.int32).tolist()
    stream_events, stream_errors = [], []
    started = threading.Event()

    def slow_reader():
        try:
            for ev in fleet.generate_stream(probe, max_new=6):
                started.set()
                stream_events.append(ev)
                if "token" in ev:
                    time.sleep(0.05)     # slower than the decode loop
        except Exception as e:  # noqa: BLE001 — surfaced in gates
            stream_errors.append(repr(e))
            started.set()

    reader = threading.Thread(target=slow_reader)
    reader.start()
    started.wait(10.0)
    victim = None
    deadline = time.time() + 5
    while time.time() < deadline and victim is None:
        for m in fleet.router.members():
            if m["in_flight"] > 0:
                victim = m["name"]
                break
        if victim is None:
            time.sleep(0.01)
    stream_drained = (fleet.retire(victim, drain=True, timeout_s=20.0)
                      if victim is not None else False)
    reader.join(30.0)
    fleet.stop()

    sc = scaler.snapshot()
    tot = rep["totals"]
    quiet_row = next(r for r in rep["phases"] if r["name"] == "quiet")
    peak = max(sizes) if sizes else 1
    final = sizes[-1] if sizes else 1
    got_done = any(ev.get("done") for ev in stream_events)
    n_tokens = sum(1 for ev in stream_events if "token" in ev)

    failures = []
    if sc["scale_ups"] < 1 or peak <= 1:
        failures.append(f"fleet never grew under the flash crowd "
                        f"(scale_ups={sc['scale_ups']}, peak={peak})")
    if sc["scale_downs"] < 1 or final >= peak:
        failures.append(f"fleet never shrank after the spike "
                        f"(scale_downs={sc['scale_downs']}, "
                        f"peak={peak}, final={final})")
    if quiet_row["p95_ms"] is not None and \
            quiet_row["p95_ms"] > ascale.slo_p95_ms:
        failures.append(f"quiet-phase p95 {quiet_row['p95_ms']}ms "
                        f"blew the {ascale.slo_p95_ms}ms SLO")
    if tot["failed"] != 0:
        failures.append(f"non-shed failures: {tot['failed']} "
                        f"({tot['errors'][:3]})")
    if tot["dropped_harness"] != 0:
        failures.append(f"harness dropped {tot['dropped_harness']} "
                        f"arrivals (raise max_outstanding)")
    if victim is None:
        failures.append("drain sub-test never saw the stream's "
                        "in-flight slot")
    if stream_errors or not got_done or not stream_drained:
        failures.append(f"scale-down dropped an in-flight stream: "
                        f"errors={stream_errors}, done={got_done}, "
                        f"drained={stream_drained}, "
                        f"tokens={n_tokens}")
    if failures:
        raise RuntimeError("traffic smoke FAILED: "
                           + "; ".join(failures))

    result = {
        "metric": "traffic_smoke_quiet_p95_latency",
        "value": quiet_row["p95_ms"],
        "unit": "ms",
        "slo_p95_ms": ascale.slo_p95_ms,
        "offered": tot["offered"],
        "completed": tot["completed"],
        "shed": tot["shed"],
        "failed": tot["failed"],
        "shed_rate": tot["shed_rate"],
        "p50_ms": tot["p50_ms"],
        "p95_ms": tot["p95_ms"],
        "p99_ms": tot["p99_ms"],
        "phases": [{k: r[k] for k in ("name", "offered", "completed",
                                      "shed", "p95_ms")}
                   for r in rep["phases"]],
        "engines_start": 1,
        "engines_peak": peak,
        "engines_final": final,
        "scale_ups": sc["scale_ups"],
        "scale_downs": sc["scale_downs"],
        "holds": sc["holds"],
        "aborts": sc["aborts"],
        "drained_clean": sc["drained_clean"],
        "drain_timeouts": sc["drain_timeouts"],
        "stream_drain_tokens": n_tokens,
        "stream_drained": stream_drained,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_tail_smoke(out=None):
    """ISSUE 12 acceptance: tail-tolerant serving on CPU, three legs —
    the run FAILS (raises) unless every gate holds:
      * HEDGE leg: two identical 3-engine fleets, one engine in each
        turned into a straggler (`set_stall`); identical closed-loop
        traffic.  Gates: hedged p99 <= 0.5x unhedged p99 (hedging cut
        the tail at least 2x) with hedges <= 10% of routed (the
        retry-budget bound, observed not just promised);
      * BROWNOUT leg: a 2-engine fleet under open-loop overload with a
        1:1:1 interactive/batch/best_effort mix.  Gates: retry
        amplification (attempts/routed) <= 1.2x, interactive p95
        holds the SLO while best_effort sheds (brownout engaged);
      * DOA leg: requests arriving with an already-expired deadline
        are counted `expired_on_arrival` and burn ZERO engine steps.
    Records both p99s, the hedge rate, amplification, per-class
    sheds/latency, and the DOA accounting; `out` writes the JSON line
    to a file as well (scripts/tail_smoke.sh -> BENCH_pr12.json)."""
    import tempfile
    import threading

    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import (DeadlineExpired, EngineFleet,
                                 RouterSpec, ServeSpec)
    from singa_tpu.serve.traffic import TrafficGen, stall_chaos, steady
    from singa_tpu.utils.checkpoint import CheckpointManager

    vocab, seq = 64, 16
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))

    def make_fleet(size, router_spec, queue_capacity=8):
        ws = tempfile.mkdtemp(prefix="tail_smoke_")
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        mgr.save(1, params, {"t": np.zeros(())},
                 health={"verdict": "ok"})
        spec = ServeSpec(buckets=((2, seq),), max_new_tokens=4,
                         batch_window_s=0.002, request_timeout_s=30.0,
                         queue_capacity=queue_capacity, cb="on",
                         cb_slots=2, cb_block_len=4)
        fleet = EngineFleet.local(net, spec, size, workspace=ws,
                                  params=params,
                                  router_spec=router_spec,
                                  log_fn=lambda s: None)
        fleet.start()
        return fleet

    # -- leg 1: hedged vs unhedged tail under one straggler -----------
    def hedge_leg(hedge):
        rspec = RouterSpec(probe_period_s=0.05, quarantine_after=10,
                           request_timeout_s=30.0, hedge=hedge,
                           hedge_min_s=0.1, hedge_max_s=0.25)
        fleet = make_fleet(3, rspec)
        stall_chaos(fleet, stall_s=0.25)()   # latch the straggler
        lats, errors = [], []
        lock = threading.Lock()

        def worker(i):
            rng = np.random.default_rng(100 + i)
            for _ in range(30):
                toks = rng.integers(1, vocab, size=4).tolist()
                t0 = time.monotonic()
                try:
                    fleet.generate(toks)
                except Exception as e:  # noqa: BLE001 — gated below
                    with lock:
                        errors.append(repr(e))
                    continue
                with lock:
                    lats.append(time.monotonic() - t0)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        snap = fleet.router.stats.snapshot()
        cancelled = sum(fleet.router.handle_for(n).engine
                        .stats.cancelled
                        for n in fleet.router.names())
        fleet.stop()
        lats.sort()
        p99 = (lats[min(int(0.99 * len(lats)), len(lats) - 1)] * 1e3
               if lats else None)
        return {"p99_ms": round(p99, 3) if p99 else None,
                "completed": len(lats), "errors": errors,
                "routed": snap["routed"], "hedges": snap["hedges"],
                "hedge_wins": snap["hedge_wins"],
                "cancelled": cancelled}

    unhedged = hedge_leg("off")
    hedged = hedge_leg("on")
    hedge_rate = hedged["hedges"] / max(hedged["routed"], 1)
    tail_ratio = (hedged["p99_ms"] / unhedged["p99_ms"]
                  if hedged["p99_ms"] and unhedged["p99_ms"]
                  else None)

    # -- leg 2: brownout under an open-loop overload with a QoS mix ---
    slo_p95_ms = 2000.0
    rspec = RouterSpec(probe_period_s=0.05, quarantine_after=10,
                       request_timeout_s=30.0, hedge="off",
                       brownout_shed_rate=0.05)
    fleet = make_fleet(2, rspec, queue_capacity=4)
    for n in fleet.router.names():     # throttle so the offered load
        fleet.router.handle_for(n).engine.set_stall(0.02)  # saturates
    gen = TrafficGen(
        lambda toks, priority="interactive": fleet.generate(
            toks.tolist(), priority=priority),
        vocab=vocab, seed=0, max_outstanding=512,
        log_fn=lambda s: None)
    rep = gen.run([steady("overload", duration_s=4.0, rate_rps=150.0,
                          prompt_lens=(4,), max_new=(4,),
                          priorities=("interactive", "batch",
                                      "best_effort"),
                          priority_weights=(1.0, 1.0, 1.0))],
                  drain_timeout_s=60.0)
    rsnap = fleet.router.stats.snapshot()
    amplification = rsnap["attempts"] / max(rsnap["routed"], 1)
    by_class = rep["totals"]["by_class"]
    inter_p95 = (by_class.get("interactive") or {}).get("p95_ms")
    be_sheds = (rsnap["shed_best_effort"]
                + sum(fleet.router.handle_for(n).engine
                      .stats.shed_best_effort
                      for n in fleet.router.names()))

    # -- leg 3: dead on arrival burns zero engine steps ---------------
    idle_deadline = time.time() + 30
    while time.time() < idle_deadline and any(
            m["in_flight"] > 0 for m in fleet.router.members()):
        time.sleep(0.05)
    time.sleep(0.3)                      # let the decode loops drain
    doa_before = rsnap["expired_on_arrival"]

    def engine_steps():
        return sum(fleet.router.handle_for(n).engine.stats.cb_steps
                   for n in fleet.router.names())

    steps_before = engine_steps()
    doa_n = 5
    doa_refused = 0
    dead = time.monotonic() - 1.0
    for _ in range(doa_n):
        try:
            fleet.generate([1, 2, 3], deadline=dead)
        except DeadlineExpired:
            doa_refused += 1
    time.sleep(0.2)
    steps_after = engine_steps()
    expired = (fleet.router.stats.snapshot()["expired_on_arrival"]
               - doa_before)
    doa_steps_burned = steps_after - steps_before
    fleet.stop()

    gates = {
        "tail_ratio": {"value": tail_ratio, "bound": 0.5,
                       "op": "<=",
                       "pass": bool(tail_ratio is not None
                                    and tail_ratio <= 0.5)},
        "hedge_rate": {"value": round(hedge_rate, 4), "bound": 0.10,
                       "op": "<=", "pass": bool(hedge_rate <= 0.10)},
        "retry_amplification": {
            "value": round(amplification, 4), "bound": 1.2,
            "op": "<=", "pass": bool(amplification <= 1.2)},
        "interactive_p95": {
            "value": inter_p95, "bound": slo_p95_ms, "op": "<=",
            "pass": bool(inter_p95 is not None
                         and inter_p95 <= slo_p95_ms)},
        "best_effort_sheds": {"value": be_sheds, "bound": 1,
                              "op": ">=",
                              "pass": bool(be_sheds >= 1)},
        "expired_on_arrival": {"value": expired, "bound": doa_n,
                               "op": "==",
                               "pass": bool(expired == doa_n
                                            and doa_refused == doa_n)},
        "doa_zero_steps": {"value": doa_steps_burned, "bound": 0,
                           "op": "==",
                           "pass": bool(doa_steps_burned == 0)},
    }
    failures = [f"{k}: {g['value']} not {g['op']} {g['bound']}"
                for k, g in gates.items() if not g["pass"]]
    if unhedged["errors"] or hedged["errors"]:
        failures.append(f"hedge legs saw non-shed failures: "
                        f"{(unhedged['errors'] + hedged['errors'])[:3]}")
    if rep["totals"]["failed"] != 0:
        failures.append(f"brownout leg non-shed failures: "
                        f"{rep['totals']['errors'][:3]}")
    if failures:
        raise RuntimeError("tail smoke FAILED: " + "; ".join(failures))

    result = {
        "metric": "tail_smoke_p99_ratio",
        "value": round(tail_ratio, 4),
        "unit": "x",
        "hedged_p99_ms": hedged["p99_ms"],
        "unhedged_p99_ms": unhedged["p99_ms"],
        "hedge_rate": round(hedge_rate, 4),
        "hedges": hedged["hedges"],
        "hedge_wins": hedged["hedge_wins"],
        "cancelled": hedged["cancelled"],
        "retry_amplification": round(amplification, 4),
        "interactive_p95_ms": inter_p95,
        "slo_p95_ms": slo_p95_ms,
        "best_effort_sheds": be_sheds,
        "brownout_sheds": rsnap["brownout_sheds"],
        "shed_by_class": {
            "interactive": rsnap["shed_interactive"],
            "batch": rsnap["shed_batch"],
            "best_effort": rsnap["shed_best_effort"]},
        "offered": rep["totals"]["offered"],
        "completed": rep["totals"]["completed"],
        "shed": rep["totals"]["shed"],
        "expired_on_arrival": expired,
        "doa_steps_burned": doa_steps_burned,
        "gates": gates,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_failover_smoke(out=None):
    """Mid-stream failover proof (PR13, docs/SERVING.md): durable
    decode sessions survive engine death.  Three legs on local
    fleets pinned to one checkpoint fingerprint:

      * KILL leg: 3 concurrent 1024-token streams over 2 engines;
        the engine holding the most live streams is killed once every
        client has tokens in hand.  Gates: zero client-visible stream
        failures, zero duplicate and zero missing sequence numbers
        across all clients (exactly-once), >= 1 spliced terminal, and
        every spliced stream BIT-IDENTICAL to an uninterrupted
        reference decode of the same prompt (greedy determinism);
      * RESUME-FAULT leg: same crash with `serve.resume@0:error`
        injected — the resume attempt is abandoned and the stream
        degrades to the pre-failover terminal error (never a hang,
        never a duplicate token);
      * WATCHDOG leg: the serving engine goes silent mid-stream
        (`set_stall`, the engine.stall shape: alive, probing ok,
        producing nothing) — the per-stream idle watchdog
        (`stream_idle_s`) fails the stream over and it still finishes
        bit-identical.
    `out` writes the JSON line to a file as well
    (scripts/failover_smoke.sh -> BENCH_pr13.json)."""
    import tempfile
    import threading

    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import EngineFleet, RouterSpec, ServeSpec
    from singa_tpu.utils.checkpoint import CheckpointManager
    from singa_tpu.utils.faults import FaultSchedule, inject

    vocab, plen, max_new = 64, 4, 1024
    seq = 1040                       # net horizon >= plen + max_new
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))

    def make_fleet(size, stream_idle_s=0.0):
        ws = tempfile.mkdtemp(prefix="failover_smoke_")
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        mgr.save(1, params, {"t": np.zeros(())},
                 health={"verdict": "ok"})
        spec = ServeSpec(buckets=((2, seq),), max_new_tokens=max_new,
                         batch_window_s=0.002,
                         request_timeout_s=120.0, cb="on",
                         cb_slots=3, cb_block_len=64)
        rspec = RouterSpec(probe_period_s=0.1, quarantine_after=5,
                           request_timeout_s=120.0, hedge="off",
                           stream_idle_s=stream_idle_s)
        fleet = EngineFleet.local(net, spec, size, workspace=ws,
                                  params=params, router_spec=rspec,
                                  log_fn=lambda s: None)
        fleet.start()
        return fleet

    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, vocab, size=plen).tolist()
               for _ in range(3)]

    # -- reference: uninterrupted greedy decode per prompt ------------
    fleet = make_fleet(1)
    reference = []
    for p in prompts:
        done = None
        for ev in fleet.generate_stream(p, max_new=max_new,
                                        timeout=300.0):
            if ev.get("done"):
                done = ev
        reference.append(done["tokens"])
    fleet.stop()

    def run_streams(fleet, n, mnew, kill_after=None, chaos=None):
        """n concurrent streams of `mnew` tokens; once EVERY stream
        has >= kill_after tokens in hand, `chaos(victim)` hits the
        engine holding the most live streams.  Returns (per-client
        audits, victim)."""
        results = [None] * n
        counts = [0] * n
        lock = threading.Lock()
        hit = {"victim": None}

        def strike_when_ready():
            while True:
                with lock:
                    if all(c >= kill_after for c in counts):
                        break
                    if all(r is not None for r in results):
                        return       # finished before chaos armed
                time.sleep(0.002)
            by_eng = {}
            for s in fleet.router.sessions.snapshot()["sessions"]:
                by_eng[s["engine"]] = by_eng.get(s["engine"], 0) + 1
            if not by_eng:
                return
            victim = max(sorted(by_eng), key=by_eng.get)
            hit["victim"] = victim
            chaos(victim)

        def client(k):
            seen, toks, done, err = [], [], None, None
            try:
                for ev in fleet.generate_stream(prompts[k],
                                                max_new=mnew,
                                                timeout=300.0):
                    if ev.get("done"):
                        done = ev
                        continue
                    seen.append(int(ev["i"]))
                    toks.append(int(ev["token"]))
                    with lock:
                        counts[k] += 1
            except Exception as e:  # noqa: BLE001 — gated below
                err = f"{type(e).__name__}: {e}"
            with lock:
                results[k] = {"seen": seen, "toks": toks,
                              "done": done, "err": err}

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(n)]
        for t in threads:
            t.start()
        if chaos is not None:
            threading.Thread(target=strike_when_ready,
                             daemon=True).start()
        for t in threads:
            t.join(600.0)
        if any(r is None for r in results):
            raise RuntimeError("failover smoke: a client HUNG "
                               "(stream neither finished nor failed)")
        return results, hit["victim"]

    def audit(results, mnew):
        failures = sum(1 for a in results
                       if a["err"] or a["done"] is None)
        dup = sum(len(a["seen"]) - len(set(a["seen"]))
                  for a in results)
        missing = sum(len(set(range(mnew)) - set(a["seen"]))
                      for a in results)
        return failures, dup, missing

    # -- leg 1: kill the engine holding live 1024-token streams -------
    fleet = make_fleet(2)
    res, victim = run_streams(
        fleet, 3, max_new, kill_after=64,
        chaos=lambda v: fleet.router.handle_for(v).kill())
    kill_snap = fleet.router.sessions.stats.snapshot()
    fleet.stop()
    k_fail, k_dup, k_missing = audit(res, max_new)
    k_spliced = sum(1 for a in res
                    if (a["done"] or {}).get("spliced"))
    k_parity = sum(
        1 for a, ref in zip(res, reference)
        if a["toks"] != ref or (a["done"] or {}).get("tokens") != ref)

    # -- leg 2: injected serve.resume fault degrades, never hangs -----
    fleet = make_fleet(2)
    with inject(FaultSchedule.parse("serve.resume@0:error")):
        res_f, _ = run_streams(
            fleet, 1, 256, kill_after=32,
            chaos=lambda v: fleet.router.handle_for(v).kill())
    fault_snap = fleet.router.sessions.stats.snapshot()
    fleet.stop()
    f_terminal = int(res_f[0]["err"] is not None
                     and res_f[0]["done"] is None)
    _, f_dup, _ = audit(res_f, 256)

    # -- leg 3: silent stall -> idle watchdog -> resume ---------------
    fleet = make_fleet(2, stream_idle_s=0.5)
    res_w, _ = run_streams(
        fleet, 1, 256, kill_after=32,
        chaos=lambda v: fleet.router.handle_for(v)
        .engine.set_stall(10.0))
    watch_snap = fleet.router.sessions.stats.snapshot()
    fleet.stop()
    w_fail, w_dup, w_missing = audit(res_w, 256)
    w_parity = int(res_w[0]["toks"] != reference[0][:256])
    w_resumed = int(watch_snap["idle_timeouts"] >= 1
                    and watch_snap["resumed"] >= 1 and not w_fail)

    gates = {
        "failover_stream_failures": {
            "value": k_fail, "bound": 0, "op": "==",
            "pass": bool(k_fail == 0)},
        "failover_dup_tokens": {
            "value": k_dup, "bound": 0, "op": "==",
            "pass": bool(k_dup == 0)},
        "failover_missing_tokens": {
            "value": k_missing, "bound": 0, "op": "==",
            "pass": bool(k_missing == 0)},
        "failover_spliced_streams": {
            "value": k_spliced, "bound": 1, "op": ">=",
            "pass": bool(k_spliced >= 1)},
        "failover_parity_mismatch": {
            "value": k_parity, "bound": 0, "op": "==",
            "pass": bool(k_parity == 0)},
        "resume_fault_terminal": {
            "value": f_terminal, "bound": 1, "op": "==",
            "pass": bool(f_terminal == 1
                         and fault_snap["resume_faults"] >= 1)},
        "resume_fault_dup_tokens": {
            "value": f_dup, "bound": 0, "op": "==",
            "pass": bool(f_dup == 0)},
        "idle_watchdog_resumed": {
            "value": w_resumed, "bound": 1, "op": "==",
            "pass": bool(w_resumed == 1 and w_dup == 0
                         and w_missing == 0 and w_parity == 0)},
    }
    failures = [f"{k}: {g['value']} not {g['op']} {g['bound']}"
                for k, g in gates.items() if not g["pass"]]
    if failures:
        raise RuntimeError("failover smoke FAILED: "
                           + "; ".join(failures))

    result = {
        "metric": "failover_exactly_once_streams",
        "value": len(res),
        "unit": "streams",
        "stream_tokens": max_new,
        "victim": victim,
        "kill_leg": {"failures": k_fail, "dup": k_dup,
                     "missing": k_missing, "spliced": k_spliced,
                     "parity_mismatch": k_parity,
                     "sessions": kill_snap},
        "resume_fault_leg": {"terminal": f_terminal, "dup": f_dup,
                             "error": res_f[0]["err"],
                             "sessions": fault_snap},
        "watchdog_leg": {"failures": w_fail, "dup": w_dup,
                         "missing": w_missing,
                         "parity_mismatch": w_parity,
                         "sessions": watch_snap},
        "gates": gates,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_transport_smoke(out=None):
    """ISSUE 20 acceptance (docs/SERVING.md "Wire protocol"): the
    zero-copy binary transport against the HTTP/JSON debug surface.
    Five legs on one warm engine (cb=on) plus a two-engine fleet:

      * A/B leg: interleaved closed-loop unary decodes over ONE
        persistent binary connection vs the keep-alive HTTP handle.
        Gates: binary p50 < HTTP p50, and the `singa_wire_*`
        serialization-time split shows the binary encode path
        spending LESS wall time than the JSON path spends per token
        (where the saved time comes from);
      * PARITY leg: the streamed token sequence over the binary
        transport is BIT-IDENTICAL to the HTTP ndjson stream and to
        the unary result (greedy determinism across transports);
      * SPLICE leg: a mixed fleet (one binary-capable engine, one
        HTTP-only) loses the binary engine mid-stream — the session
        machinery splices the remainder from the HTTP sibling with
        zero client-visible failures, zero duplicate and zero missing
        tokens, bit-identical to an uninterrupted reference;
      * FUZZ leg: garbage magic, truncations at every cut point,
        oversized length prefixes and random bytes against the live
        listener — every one is a counted `wire_malformed_total`
        close within the timeout, never a hang, and the listener
        keeps serving;
      * FAULT leg: `wire.frame` drop/corrupt/tear injected on the
        binary path — the negotiating handle absorbs each one by
        falling back to HTTP with zero client-visible failures.
    `out` writes the JSON line to a file as well
    (scripts/transport_smoke.sh -> BENCH_pr20.json)."""
    import socket as _socket
    import tempfile
    import threading

    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import (BinaryEngineHandle, EngineFleet,
                                 HttpEngineHandle, InferenceEngine,
                                 InferenceServer,
                                 NegotiatingEngineHandle, RouterSpec,
                                 ServeSpec, wire)
    from singa_tpu.utils.checkpoint import CheckpointManager
    from singa_tpu.utils.faults import FaultSchedule, inject

    vocab, plen, seq = 64, 4, 64
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    spec = ServeSpec(buckets=((2, seq),), max_new_tokens=32,
                     batch_window_s=0.002, request_timeout_s=60.0,
                     cb="on", cb_slots=3, cb_block_len=8)

    def make_server(wire_on=True):
        eng = InferenceEngine(net, spec, params=params,
                              log_fn=lambda s: None)
        srv = InferenceServer(eng, port=0, wire_on=wire_on,
                              log_fn=lambda s: None)
        srv.start()
        return srv

    prompt = np.arange(1, 1 + plen, dtype=np.int32)
    srv = make_server(wire_on=True)
    host, port = srv.address
    hh = HttpEngineHandle("e0", f"http://{host}:{port}")
    bh = BinaryEngineHandle("e0", srv.wire_address)

    # -- A/B leg: interleaved closed-loop unary decodes ---------------
    n_ab = 40
    for _ in range(4):                   # warm both paths + compile
        hh.request("generate", prompt, timeout=30)
        bh.request("generate", prompt, timeout=30)
    lat = {"http": [], "binary": []}
    for _ in range(n_ab):
        for name, h in (("http", hh), ("binary", bh)):
            t0 = time.perf_counter()
            h.request("generate", prompt, timeout=30)
            lat[name].append(time.perf_counter() - t0)
    p50_http = float(np.median(lat["http"]) * 1e3)
    p50_bin = float(np.median(lat["binary"]) * 1e3)

    # serialization split: stream the SAME decode over each transport
    # and charge the per-token encode cost to its own accumulator
    def _delta(before, after, *keys):
        return sum(after[k] - before[k] for k in keys)

    s_tokens = 32
    pre = wire.STATS.snapshot()
    http_stream = [ev for ev in hh.request_stream(
        prompt, timeout=60, max_new=s_tokens)]
    mid = wire.STATS.snapshot()
    bin_stream = [ev for ev in bh.request_stream(
        prompt, timeout=60, max_new=s_tokens)]
    post = wire.STATS.snapshot()
    ser_http_s = _delta(pre, mid, "json_ser_seconds",
                        "ser_seconds")
    ser_bin_s = _delta(mid, post, "json_ser_seconds", "ser_seconds")
    flushes = _delta(pre, post, "token_flushes")

    # -- PARITY leg ---------------------------------------------------
    ref = hh.request("generate", prompt, timeout=30)["tokens"]
    h_toks = [ev["token"] for ev in http_stream if "done" not in ev]
    b_toks = [ev["token"] for ev in bin_stream if "done" not in ev]
    parity_mismatch = int(h_toks != ref) + int(b_toks != ref)

    # -- FUZZ leg -----------------------------------------------------
    whole = b"".join(bytes(p) for p in wire.frame_parts(
        wire.K_REQ, 7, wire.encode_qos_header(tenant="t"),
        [wire.encode_request(wire.OP_GENERATE, [1, 2, 3])]))
    rng = np.random.default_rng(11)
    cases = [b"XX" + b"\x00" * 14,
             wire._PREAMBLE.pack(wire.MAGIC, wire.VERSION + 1,
                                 wire.K_HELLO, 0, 0, 1, 0, 0),
             wire._PREAMBLE.pack(wire.MAGIC, wire.VERSION,
                                 wire.K_REQ, 0, 0, 1, 0,
                                 wire.MAX_PAYLOAD_LEN + 1)]
    cases += [whole[:cut] for cut in range(1, len(whole), 7)]
    cases += [rng.integers(0, 256, int(rng.integers(1, 48)))
              .astype(np.uint8).tobytes() for _ in range(25)]
    fuzz_pre = wire.STATS.snapshot()["malformed"]
    fuzz_hangs = 0
    for raw in cases:
        s = _socket.create_connection(srv.wire_address, timeout=5.0)
        try:
            s.sendall(raw)
            s.shutdown(_socket.SHUT_WR)  # half-close: no more bytes
            s.settimeout(5.0)
            while s.recv(4096):          # drain until peer closes
                pass
        except (TimeoutError, _socket.timeout):
            fuzz_hangs += 1
        except OSError:
            pass                         # reset counts as closed
        finally:
            s.close()
    fuzz_malformed = wire.STATS.snapshot()["malformed"] - fuzz_pre
    fuzz_survived = int(bh.probe().get("ok", False))
    hh.close()
    bh.close()

    # -- FAULT leg: wire.frame absorbed by HTTP fallback --------------
    fault_failures = 0
    fault_pre = wire.STATS.snapshot()["faulted_frames"]
    for kind in ("error", "corrupt", "torn"):
        nh = NegotiatingEngineHandle("e0", f"http://{host}:{port}",
                                     connect_timeout_s=3.0,
                                     log_fn=lambda s: None)
        try:
            nh.probe()
            with inject(FaultSchedule.parse(f"wire.frame@0:{kind}")):
                got = nh.request("generate", prompt, timeout=30)
            if len(got["tokens"]) != s_tokens:
                fault_failures += 1
        except Exception:  # noqa: BLE001 — gated below
            fault_failures += 1
        finally:
            nh.close()
    faulted = wire.STATS.snapshot()["faulted_frames"] - fault_pre
    srv.stop()

    # -- SPLICE leg: mixed fleet loses the binary engine mid-stream ---
    s_max = 32
    a = make_server(wire_on=True)
    b = make_server(wire_on=False)
    ws = tempfile.mkdtemp(prefix="transport_smoke_")
    CheckpointManager(ws, log_fn=lambda s: None).save(
        1, params, {"t": np.zeros(())}, health={"verdict": "ok"})
    rspec = RouterSpec(probe_period_s=0.1, hedge="off",
                       request_timeout_s=60.0, wal_group_tokens=4,
                       wal_group_ms=5.0, state_snapshot_s=0.1)
    fleet = EngineFleet.adopt(
        [f"http://{h}:{p}" for h, p in (a.address, b.address)],
        workspace=ws, router_spec=rspec, log_fn=lambda s: None)
    splice_failures, splice_dup, splice_missing = 1, 0, 0
    splice_parity = 1
    try:
        fleet.start()
        deadline = time.monotonic() + 10.0
        h0 = fleet.router.handle_for("engine-0")
        while time.monotonic() < deadline and \
                h0.transport != "binary":
            time.sleep(0.05)
        splice_transport = h0.transport
        sref = [ev["token"]
                for ev in fleet.generate_stream(prompt,
                                                max_new=s_max)
                if "token" in ev]
        seen, idx, killed, err = [], [], False, None
        try:
            for ev in fleet.generate_stream(prompt, max_new=s_max):
                if "token" not in ev:
                    continue
                seen.append(int(ev["token"]))
                idx.append(int(ev["i"]))
                if len(seen) == 4 and not killed:
                    killed = True
                    a.stop()             # the whole binary worker
        except Exception as e:  # noqa: BLE001 — gated below
            err = f"{type(e).__name__}: {e}"
        splice_failures = int(err is not None)
        splice_dup = len(idx) - len(set(idx))
        splice_missing = len(set(range(s_max)) - set(idx))
        splice_parity = int(seen != sref)
    finally:
        fleet.stop()
        b.stop()
        try:
            a.stop()
        except Exception:  # noqa: BLE001 — may already be down
            pass

    gates = {
        "transport_p50_improved": {
            "value": round(p50_bin, 3), "bound": round(p50_http, 3),
            "op": "<", "pass": bool(p50_bin < p50_http)},
        "transport_ser_time_reduced": {
            "value": round(ser_bin_s * 1e6, 1),
            "bound": round(ser_http_s * 1e6, 1), "op": "<",
            "pass": bool(ser_bin_s < ser_http_s)},
        "transport_stream_parity": {
            "value": parity_mismatch, "bound": 0, "op": "==",
            "pass": bool(parity_mismatch == 0)},
        "wire_splice_exactly_once": {
            "value": splice_failures + splice_dup + splice_missing
            + splice_parity, "bound": 0, "op": "==",
            "pass": bool(splice_failures == 0 and splice_dup == 0
                         and splice_missing == 0
                         and splice_parity == 0)},
        "wire_fuzz_no_hangs": {
            "value": fuzz_hangs, "bound": 0, "op": "==",
            "pass": bool(fuzz_hangs == 0
                         and fuzz_malformed >= len(cases) - 2
                         and fuzz_survived)},
        "wire_fault_absorbed": {
            "value": fault_failures, "bound": 0, "op": "==",
            "pass": bool(fault_failures == 0 and faulted >= 3)},
    }
    failures = [f"{k}: {g['value']} not {g['op']} {g['bound']}"
                for k, g in gates.items() if not g["pass"]]
    if failures:
        raise RuntimeError("transport smoke FAILED: "
                           + "; ".join(failures))

    result = {
        "metric": "transport_p50_ms",
        "value": round(p50_bin, 3),
        "unit": "ms",
        "http_p50_ms": round(p50_http, 3),
        "requests_per_leg": n_ab,
        "ab_leg": {
            "binary_p50_ms": round(p50_bin, 3),
            "http_p50_ms": round(p50_http, 3),
            "binary_ser_us": round(ser_bin_s * 1e6, 1),
            "http_ser_us": round(ser_http_s * 1e6, 1),
            "stream_tokens": s_tokens,
            "token_flushes": flushes},
        "parity_leg": {"mismatch": parity_mismatch,
                       "tokens": len(ref)},
        "splice_leg": {"failures": splice_failures,
                       "dup": splice_dup,
                       "missing": splice_missing,
                       "parity_mismatch": splice_parity,
                       "transport_before_kill": splice_transport},
        "fuzz_leg": {"cases": len(cases), "hangs": fuzz_hangs,
                     "malformed_counted": fuzz_malformed,
                     "listener_survived": fuzz_survived},
        "fault_leg": {"client_failures": fault_failures,
                      "faulted_frames": faulted},
        "wire_stats": wire.STATS.snapshot(),
        "gates": gates,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_router_smoke(out=None):
    """ISSUE 19 acceptance (docs/SERVING.md "Control-plane
    durability"): the crash-safe control plane.  Five legs:

      * RESTART leg (real SIGKILL, over HTTP): a one-worker fleet
        router subprocess serves 3 concurrent 256-token streams;
        once every client holds >= 32 tokens the router is SIGKILLed
        — no atexit, no close records: the journal tail is whatever
        the last group commit made durable — then restarted on the
        same port over the same workspace.  Every client reconnects
        with its session id + resume_from.  Gates: zero
        client-visible failures, zero duplicate and zero missing
        indices across the reconnect (exactly-once), every spliced
        stream BIT-IDENTICAL to an uninterrupted reference, >= 3
        streams recovered from the WAL;
      * HANDOFF leg (over HTTP): primary + warm `standby=True`
        router share one workspace; POST /admin/handoff mid-stream
        lame-ducks the primary (the in-flight stream finishes; a
        fresh admission gets 409 + the successor URL), POST
        /admin/promote fences the old epoch and the promoted standby
        serves the same prompt bit-identically;
      * STATE leg: a quarantine bench and a per-(tenant, class) shed
        streak survive an in-process router rebuild over the same
        workspace — the control-state snapshot closes the
        restart-launders-strikes hole;
      * OVERHEAD leg: interleaved A/B of wal=on vs wal=off fleets,
        gate: median stream tok/s ratio >= 0.97 (the WAL must cost
        <= 3% of streaming throughput);
      * WAL-FAULT leg: `router.wal@0:error` — the faulted group
        commit degrades to counted lost durability (`wal_lost`); the
        stream completes, a disk error never blocks a token.
    `out` writes the JSON line (scripts/router_smoke.sh ->
    BENCH_pr19.json)."""
    import signal
    import socket
    import subprocess
    import sys
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import jax

    from singa_tpu.config import load_model_config
    from singa_tpu.core.net import build_net
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.data import discover_input_shapes
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import EngineFleet, FleetServer, RouterSpec, \
        ServeSpec
    from singa_tpu.utils.checkpoint import CheckpointManager
    from singa_tpu.utils.faults import FaultSchedule, inject

    vocab, plen, max_new = 64, 4, 256
    seq = 272                        # net horizon >= plen + max_new
    repo = os.path.dirname(os.path.abspath(__file__))

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def http_json(url, body=None, timeout=60.0):
        req = urllib.request.Request(
            url, data=(json.dumps(body).encode()
                       if body is not None else None),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())

    def http_stream(url, body, timeout=120.0):
        req = urllib.request.Request(
            url + "/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=timeout)

    # ---- leg 1: real SIGKILL restart over HTTP ----------------------
    ws = tempfile.mkdtemp(prefix="router_smoke_")
    with open(os.path.join(
            repo, "examples/transformer/lm_tiny.conf")) as f:
        conf_txt = f.read().replace("seq_len: 16", f"seq_len: {seq}")
    conf = os.path.join(ws, "lm_smoke.conf")
    with open(conf, "w") as f:
        f.write(conf_txt)
    model = load_model_config(conf)
    shapes = discover_input_shapes(model, force_synthetic=True)
    trainer = Trainer(model, shapes, log_fn=lambda s: None)
    conf_net = trainer.test_net or trainer.train_net
    conf_params = conf_net.init_params(jax.random.PRNGKey(0))
    CheckpointManager(ws, log_fn=lambda s: None).save(
        1, conf_params, {"t": np.zeros(())},
        health={"verdict": "ok"})

    port = free_port()
    url = f"http://127.0.0.1:{port}"
    cmd = [sys.executable, "-m", "singa_tpu.main", "serve",
           "-model_conf", conf, "--workspace", ws,
           "--fleet", "1", "--port", str(port),
           "--serve_spec",
           f"buckets=4x{seq},max_new_tokens={max_new},"
           "batch_window_s=0.002,cb=on,cb_slots=4,cb_block_len=16",
           "--fleet_spec",
           "probe_period_s=0.2,hedge=off,request_timeout_s=120,"
           "wal_group_tokens=8,wal_group_ms=5,state_snapshot_s=0.2"]
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))

    def launch():
        return subprocess.Popen(cmd, cwd=repo, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def wait_healthy(proc, secs=600.0):
        deadline = time.time() + secs
        while True:
            if proc.poll() is not None:
                raise RuntimeError("router subprocess exited before "
                                   "serving /healthz")
            try:
                st, _ = http_json(url + "/healthz", timeout=2.0)
                if st == 200:
                    return
            except Exception:
                pass
            if time.time() > deadline:
                proc.kill()
                raise RuntimeError("router subprocess never became "
                                   "healthy")
            time.sleep(0.25)

    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, vocab, size=plen).tolist()
               for _ in range(3)]
    proc = launch()
    try:
        wait_healthy(proc)
        ref_http = []
        for p in prompts:
            toks = []
            with http_stream(url, {"tokens": p, "stream": True,
                                   "max_new": max_new}) as r:
                for line in r:
                    ev = json.loads(line)
                    if "token" in ev:
                        toks.append(int(ev["token"]))
            ref_http.append(toks)

        counts = [0] * 3
        results = [None] * 3
        lock = threading.Lock()

        def client(k):
            sid, seen, toks, err = None, [], [], None
            try:
                r = http_stream(url, {"tokens": prompts[k],
                                      "stream": True,
                                      "max_new": max_new})
                for line in r:
                    ev = json.loads(line)
                    if sid is None and "sid" in ev:
                        sid = ev["sid"]
                    if "token" in ev:
                        seen.append(int(ev["i"]))
                        toks.append(int(ev["token"]))
                        with lock:
                            counts[k] += 1
            except Exception as e:  # noqa: BLE001 — the SIGKILL cuts
                err = f"{type(e).__name__}: {e}"   # the connection
            with lock:
                results[k] = {"sid": sid, "seen": seen, "toks": toks,
                              "err": err}

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        while True:
            with lock:
                if all(c >= 32 for c in counts):
                    break
            time.sleep(0.005)
        time.sleep(0.2)              # let a group commit reach disk
        proc.send_signal(signal.SIGKILL)
        proc.wait(30)
        for t in threads:
            t.join(60.0)

        proc = launch()
        wait_healthy(proc)
        r_fail = r_dup = r_missing = r_parity = 0
        for k, res in enumerate(results):
            if res is None or res["sid"] is None:
                r_fail += 1
                continue
            seen, toks = list(res["seen"]), list(res["toks"])
            try:
                with http_stream(url, {"stream": True,
                                       "session": res["sid"],
                                       "resume_from": len(seen)}) as r:
                    done = None
                    for line in r:
                        ev = json.loads(line)
                        if ev.get("done"):
                            done = ev
                        if "token" in ev:
                            seen.append(int(ev["i"]))
                            toks.append(int(ev["token"]))
            except Exception:
                r_fail += 1
                continue
            if done is None or done.get("error"):
                r_fail += 1
            r_dup += len(seen) - len(set(seen))
            r_missing += len(set(range(max_new)) - set(seen))
            if toks != ref_http[k] or \
                    (done or {}).get("tokens") != ref_http[k]:
                r_parity += 1
        _, snap = http_json(url + "/stats", timeout=10.0)
        r_recovered = int((snap.get("wal") or {})
                          .get("recovered_streams", 0))
        restart_epoch = int(snap.get("epoch", 0))
    finally:
        proc.kill()
        proc.wait(30)

    # ---- shared in-process fixture for legs 2-5 ---------------------
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))

    def make_fleet(size, ws=None, standby=False, **rkw):
        if ws is None:
            ws = tempfile.mkdtemp(prefix="router_smoke_")
            CheckpointManager(ws, log_fn=lambda s: None).save(
                1, params, {"t": np.zeros(())},
                health={"verdict": "ok"})
        spec = ServeSpec(buckets=((2, seq),), max_new_tokens=max_new,
                         batch_window_s=0.002,
                         request_timeout_s=120.0, cb="on",
                         cb_slots=3, cb_block_len=16)
        rkw.setdefault("probe_period_s", 0.1)
        rkw.setdefault("hedge", "off")
        rkw.setdefault("request_timeout_s", 120.0)
        fleet = EngineFleet.local(net, spec, size, workspace=ws,
                                  params=params,
                                  router_spec=RouterSpec(**rkw),
                                  standby=standby,
                                  log_fn=lambda s: None)
        fleet.start()
        return fleet, ws

    def run_stream(front_url, prompt, mnew):
        t0 = time.perf_counter()
        toks, done, err = [], None, None
        try:
            with http_stream(front_url, {"tokens": prompt,
                                         "stream": True,
                                         "max_new": mnew}) as r:
                for line in r:
                    ev = json.loads(line)
                    if ev.get("done"):
                        done = ev
                    if "token" in ev:
                        toks.append(int(ev["token"]))
        except Exception as e:  # noqa: BLE001 — gated below
            err = f"{type(e).__name__}: {e}"
        return {"toks": toks, "done": done, "err": err,
                "dt": time.perf_counter() - t0}

    # ---- leg 2: zero-downtime handoff over HTTP ---------------------
    primary, ws2 = make_fleet(1)
    standby, _ = make_fleet(1, ws=ws2, standby=True)
    p1, p2 = free_port(), free_port()
    url1, url2 = (f"http://127.0.0.1:{p1}", f"http://127.0.0.1:{p2}")
    front1 = FleetServer(primary, port=p1, log_fn=lambda s: None)
    front2 = FleetServer(standby, port=p2, log_fn=lambda s: None)
    front1.start()
    front2.start()
    h_fail = h_409 = h_parity = 0
    try:
        ref = run_stream(url1, prompts[0], max_new)
        if ref["err"] or ref["done"] is None:
            raise RuntimeError(f"handoff reference failed: "
                               f"{ref['err']}")
        inflight = {}

        def victim():
            inflight["res"] = run_stream(url1, prompts[0], max_new)

        vt = threading.Thread(target=victim)
        vt.start()
        time.sleep(0.3)              # mid-stream
        st, got = http_json(url1 + "/admin/handoff",
                            {"successor": url2, "retry_after": 0.2})
        if st != 200 or not got.get("lame_duck"):
            h_fail += 1
        try:
            http_json(url1 + "/generate", {"tokens": prompts[0]})
            h_fail += 1              # should have been refused
        except urllib.error.HTTPError as e:
            body = json.loads(e.read())
            if e.code == 409 and body.get("successor") == url2:
                h_409 = 1
        st, got = http_json(url2 + "/admin/promote", {})
        if st != 200 or int(got.get("epoch", 0)) < 2:
            h_fail += 1
        vt.join(300.0)
        res = inflight.get("res")
        if res is None or res["err"] or res["done"] is None:
            h_fail += 1              # in-flight must finish on the
        elif res["toks"] != ref["toks"]:   # lame duck
            h_parity += 1
        after = run_stream(url2, prompts[0], max_new)
        if after["err"] or after["done"] is None:
            h_fail += 1
        elif after["toks"] != ref["toks"]:
            h_parity += 1
        handoff_epoch = int(standby.epoch)
    finally:
        front1.stop()
        front2.stop()
        standby.stop()
        primary.stop()

    # ---- leg 3: control state survives a rebuild --------------------
    f1, ws3 = make_fleet(2, quarantine_after=2, probe_period_s=0.05,
                         readmit_base_s=30.0, state_snapshot_s=0.05)
    victim_name = f1.router.names()[-1]
    f1.router.handle_for(victim_name).kill()
    deadline = time.time() + 30.0
    while time.time() < deadline:
        if any(m["name"] == victim_name and m["quarantined"]
               for m in f1.router.members()):
            break
        time.sleep(0.02)
    f1.router._shed_backoffs.shed_delay("interactive", tenant="acme")
    f1.router._shed_backoffs.shed_delay("interactive", tenant="acme")
    time.sleep(0.3)                  # >= several snapshot periods
    f1.stop()
    f2, _ = make_fleet(2, ws=ws3, quarantine_after=2,
                       probe_period_s=0.05, readmit_base_s=30.0,
                       state_snapshot_s=0.05)
    m2 = {m["name"]: m for m in f2.router.members()}
    s_quarantine = int(m2[victim_name]["quarantined"])
    s_streak = int(f2.router._shed_backoffs.export_streaks()
                   .get("acme\tinteractive", 0) == 2)
    f2.stop()

    # ---- leg 4: WAL overhead A/B ------------------------------------
    fleet_on, _ = make_fleet(1)
    fleet_off, _ = make_fleet(1, wal="off")
    po, pf = free_port(), free_port()
    fr_on = FleetServer(fleet_on, port=po, log_fn=lambda s: None)
    fr_off = FleetServer(fleet_off, port=pf, log_fn=lambda s: None)
    fr_on.start()
    fr_off.start()
    try:
        uo, uf = f"http://127.0.0.1:{po}", f"http://127.0.0.1:{pf}"
        run_stream(uo, prompts[0], max_new)      # warm both paths
        run_stream(uf, prompts[0], max_new)
        rates = {"on": [], "off": []}
        for _ in range(5):                       # interleaved A/B
            for key, u in (("on", uo), ("off", uf)):
                r = run_stream(u, prompts[0], max_new)
                if r["err"]:
                    raise RuntimeError(f"overhead leg stream failed "
                                       f"(wal={key}): {r['err']}")
                rates[key].append(max_new / r["dt"])
        p50_on = float(np.median(rates["on"]))
        p50_off = float(np.median(rates["off"]))
        overhead_ratio = p50_on / p50_off
    finally:
        fr_on.stop()
        fr_off.stop()
        fleet_on.stop()
        fleet_off.stop()

    # ---- leg 5: WAL write fault degrades to counted loss ------------
    with inject(FaultSchedule.parse("router.wal@0:error")):
        ff, _ = make_fleet(1)
        done = None
        for ev in ff.generate_stream(prompts[0], max_new=64,
                                     timeout=120.0):
            if ev.get("done"):
                done = ev
        ff.wal.flush()
        lost = int(ff.wal_stats.snapshot()["wal_lost"])
        fault_ok = int(done is not None and not done.get("error")
                       and len(done.get("tokens") or []) == 64)
        ff.stop()

    gates = {
        "restart_stream_failures": {
            "value": r_fail, "bound": 0, "op": "==",
            "pass": bool(r_fail == 0)},
        "restart_dup_tokens": {
            "value": r_dup, "bound": 0, "op": "==",
            "pass": bool(r_dup == 0)},
        "restart_missing_tokens": {
            "value": r_missing, "bound": 0, "op": "==",
            "pass": bool(r_missing == 0)},
        "restart_parity_mismatch": {
            "value": r_parity, "bound": 0, "op": "==",
            "pass": bool(r_parity == 0)},
        "restart_recovered_streams": {
            "value": r_recovered, "bound": 3, "op": ">=",
            "pass": bool(r_recovered >= 3)},
        "handoff_client_failures": {
            "value": h_fail, "bound": 0, "op": "==",
            "pass": bool(h_fail == 0)},
        "handoff_refusal_points_successor": {
            "value": h_409, "bound": 1, "op": "==",
            "pass": bool(h_409 == 1)},
        "handoff_parity_mismatch": {
            "value": h_parity, "bound": 0, "op": "==",
            "pass": bool(h_parity == 0)},
        "state_quarantine_survived": {
            "value": s_quarantine, "bound": 1, "op": "==",
            "pass": bool(s_quarantine == 1)},
        "state_shed_streak_survived": {
            "value": s_streak, "bound": 1, "op": "==",
            "pass": bool(s_streak == 1)},
        "wal_overhead_ratio": {
            "value": round(overhead_ratio, 4), "bound": 0.97,
            "op": ">=", "pass": bool(overhead_ratio >= 0.97)},
        "wal_fault_counted_loss": {
            "value": lost, "bound": 1, "op": ">=",
            "pass": bool(lost >= 1 and fault_ok == 1)},
    }
    failures = [f"{k}: {g['value']} not {g['op']} {g['bound']}"
                for k, g in gates.items() if not g["pass"]]
    if failures:
        raise RuntimeError("router smoke FAILED: "
                           + "; ".join(failures))

    result = {
        "metric": "router_crash_safe_streams",
        "value": r_recovered,
        "unit": "streams",
        "stream_tokens": max_new,
        "restart_leg": {"failures": r_fail, "dup": r_dup,
                        "missing": r_missing,
                        "parity_mismatch": r_parity,
                        "recovered": r_recovered,
                        "epoch_after_restart": restart_epoch},
        "handoff_leg": {"failures": h_fail,
                        "refusal_points_successor": h_409,
                        "parity_mismatch": h_parity,
                        "promoted_epoch": handoff_epoch},
        "state_leg": {"quarantine_survived": s_quarantine,
                      "shed_streak_survived": s_streak},
        "overhead_leg": {"p50_tok_s_wal_on": round(p50_on, 1),
                         "p50_tok_s_wal_off": round(p50_off, 1),
                         "ratio": round(overhead_ratio, 4)},
        "wal_fault_leg": {"wal_lost": lost, "stream_ok": fault_ok},
        "gates": gates,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_trace_smoke(out=None):
    """ISSUE 14 acceptance (docs/OBSERVABILITY.md): fleet-wide
    distributed tracing.  Three legs:

      * TRACE leg: a 3-engine local fleet with hedging forced
        (hedge_min_s = hedge_max_s = 1ms) serves one hedged unary
        request and one stream whose engine is KILLED mid-stream
        (failover resume).  The merged trace must show, PER request,
        exactly ONE trace id across every leg (primary + hedge +
        resume), spans from >= 2 engines on the failed-over stream,
        zero orphan spans, and per-stage attribution
        (admit/dispatch/first_token/decode) summing within 10% of the
        end-to-end latency;
      * FLIGHTREC leg: a fresh fleet with NO trace export — only the
        flight recorder armed — suffers the same mid-stream kill; the
        `stream.resume` trigger must dump the last events to
        `flightrec-failover-*.json` (post-mortem without tracing
        pre-enabled);
      * OVERHEAD leg: tracing-on must stay under the PR-6 < 3% wall
        gate (`bench_obs_overhead`, 2 interleaved reps).
    `out` writes the JSON line to a file as well
    (scripts/obs_smoke.sh -> BENCH_pr14.json)."""
    import glob
    import tempfile
    import threading

    import jax

    from singa_tpu import obs
    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.obs import collect
    from singa_tpu.serve import EngineFleet, RouterSpec, ServeSpec
    from singa_tpu.utils.checkpoint import CheckpointManager

    vocab, plen, max_new = 64, 4, 256
    seq = 272                        # net horizon >= plen + max_new
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(14)
    prompt = rng.integers(1, vocab, size=plen).tolist()

    def make_fleet(size):
        ws = tempfile.mkdtemp(prefix="trace_smoke_")
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        mgr.save(1, params, {"t": np.zeros(())},
                 health={"verdict": "ok"})
        spec = ServeSpec(buckets=((2, seq),), max_new_tokens=max_new,
                         batch_window_s=0.002,
                         request_timeout_s=120.0, cb="on",
                         cb_slots=3, cb_block_len=64)
        rspec = RouterSpec(probe_period_s=0.1, quarantine_after=5,
                           request_timeout_s=120.0, hedge="on",
                           hedge_min_s=0.001, hedge_max_s=0.001)
        fleet = EngineFleet.local(net, spec, size, workspace=ws,
                                  params=params, router_spec=rspec,
                                  log_fn=lambda s: None)
        fleet.start()
        return fleet

    def killed_stream(fleet, kill_after=32):
        """One stream; once `kill_after` tokens are in hand, kill the
        engine holding the session — forces a mid-stream failover."""
        count = {"n": 0}
        lock = threading.Lock()

        def strike():
            while True:
                with lock:
                    if count["n"] >= kill_after:
                        break
                    if count["n"] < 0:
                        return
                time.sleep(0.002)
            sess = fleet.router.sessions.snapshot()["sessions"]
            if sess:
                fleet.router.handle_for(sess[0]["engine"]).kill()

        threading.Thread(target=strike, daemon=True).start()
        done = None
        for ev in fleet.generate_stream(prompt, max_new=max_new,
                                        timeout=300.0):
            if ev.get("done"):
                done = ev
            else:
                with lock:
                    count["n"] += 1
        with lock:
            count["n"] = -1
        return done

    # -- leg 1: hedged unary + killed stream, trace everything --------
    tmp = tempfile.mkdtemp(prefix="trace_smoke_obs_")
    with obs.session(obs.ObsSpec(
            trace=os.path.join(tmp, "trace.json"),
            process="router", trace_ring=65536)):
        fleet = make_fleet(3)
        try:
            fleet.generate(prompt, timeout=300.0)
            done = killed_stream(fleet)
            reqs = fleet.router.requests.snapshot()["recent"]
            merged = collect.merge([obs.trace_dump()])
        finally:
            fleet.stop()
    if done is None or not (done.get("spliced") or done.get("done")):
        raise RuntimeError("trace smoke: killed stream never finished")

    # unary rows finish "ok"; stream rows finish "done" or (after a
    # failover) "spliced" — anything else is a failed request
    rows = {r["mode"]: r for r in reqs
            if r.get("outcome") in ("ok", "done", "spliced")}
    u_row, s_row = rows.get("generate"), rows.get("stream")
    if u_row is None or s_row is None:
        raise RuntimeError(f"trace smoke: missing request rows "
                           f"({sorted(rows)})")

    def span_args(pred):
        return [e["args"] for e in merged["traceEvents"]
                if e.get("ph") == "X" and pred(e)]

    # one trace id per request: every span tagged with a request's
    # corr must carry that request's trace id and no other
    ids_per_req = max(
        len({a.get("trace") for a in span_args(
            lambda e: e["args"].get("corr") == r["corr"])})
        for r in (u_row, s_row))
    s_spans = collect.spans_of(merged, s_row["trace"])
    s_names = {e["name"] for e in s_spans}
    resume_in_trace = int("router.resume" in s_names
                          and "stream.decode" in s_names
                          and "router.stream" in s_names)
    s_engines = {e["args"].get("engine") for e in s_spans
                 if e["args"].get("engine")}
    h_legs = sum(1 for e in collect.spans_of(merged, u_row["trace"])
                 if e["name"] == "router.attempt")
    n_orphans = len(collect.orphans(merged))
    stage_err = max(
        abs(1.0 - sum(r["stages_ms"].values())
            / max(r["latency_ms"], 1e-9))
        for r in (u_row, s_row))
    timeline = collect.critical_path(merged, s_row["trace"])

    # -- leg 2: flight recorder WITHOUT tracing pre-enabled -----------
    fr_dir = tempfile.mkdtemp(prefix="trace_smoke_fr_")
    with obs.session(obs.ObsSpec(flightrec=fr_dir)):
        fleet = make_fleet(2)
        try:
            killed_stream(fleet)
        finally:
            fleet.stop()
        dumps = sorted(glob.glob(
            os.path.join(fr_dir, "flightrec-failover-*.json")))
    fr_replayed = 0
    if dumps:
        with open(dumps[-1]) as f:
            fr_replayed = int("stream.resume" in f.read())

    # -- leg 3: tracing-on overhead under the PR-6 gate ---------------
    over = bench_obs_overhead(reps=2)

    gates = {
        "trace_ids_per_request": {
            "value": ids_per_req, "bound": 1, "op": "==",
            "pass": bool(ids_per_req == 1)},
        "trace_resume_in_trace": {
            "value": resume_in_trace, "bound": 1, "op": "==",
            "pass": bool(resume_in_trace == 1)},
        "trace_hedge_legs": {
            "value": h_legs, "bound": 2, "op": ">=",
            "pass": bool(h_legs >= 2)},
        "trace_engines_spanned": {
            "value": len(s_engines), "bound": 2, "op": ">=",
            "pass": bool(len(s_engines) >= 2)},
        "trace_orphan_spans": {
            "value": n_orphans, "bound": 0, "op": "==",
            "pass": bool(n_orphans == 0)},
        "stage_attribution_err": {
            "value": round(stage_err, 4), "bound": 0.10, "op": "<",
            "pass": bool(stage_err < 0.10)},
        "flightrec_replayed": {
            "value": fr_replayed, "bound": 1, "op": "==",
            "pass": bool(fr_replayed == 1)},
        "trace_overhead": {
            "value": over["value"], "bound": 0.03, "op": "<",
            "pass": bool(over["value"] < 0.03)},
    }
    failures = [f"{k}: {g['value']} not {g['op']} {g['bound']}"
                for k, g in gates.items() if not g["pass"]]
    if failures:
        raise RuntimeError("trace smoke FAILED: "
                           + "; ".join(failures))

    result = {
        "metric": "trace_smoke_merged_trace",
        "value": ids_per_req,
        "unit": "trace_ids_per_request",
        "stream": {"trace": s_row["trace"],
                   "latency_ms": s_row["latency_ms"],
                   "stages_ms": s_row["stages_ms"],
                   "resumes": s_row.get("resumes"),
                   "engines": sorted(s_engines),
                   "spans": len(s_spans)},
        "hedged_unary": {"trace": u_row["trace"],
                         "latency_ms": u_row["latency_ms"],
                         "stages_ms": u_row["stages_ms"],
                         "hedged": u_row.get("hedged"),
                         "attempt_legs": h_legs},
        "critical_path_head": timeline[:5],
        "flightrec_dumps": len(dumps),
        "obs_overhead": over["value"],
        "gates": gates,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def bench_tenant_smoke(out=None):
    """ISSUE 18 acceptance: two tenants share ONE engine (no
    autoscaler — isolation must come from quotas, not from capacity
    chasing the spike) and the run FAILS (raises) unless:
      * tenant A's flash crowd (open-loop, >= 5x B's offered rate)
        leaves tenant B untouched: B's flash-phase p95 stays within
        1.2x its quiet-phase p95 and B completes 100% of its offered
        requests with zero sheds — A's overload is A's problem;
      * A's overflow is shed honestly (Overloaded) and consecutive
        sheds carry per-tenant ESCALATING Retry-After — the backpres-
        sure signal a well-behaved client needs to back off;
      * the per-tenant retry-budget floor holds: with A's budget and
        the shared bucket drained dry, B can still spend from its
        guaranteed floor while A cannot — one tenant's retry storm
        cannot starve another's hedges;
      * zero non-shed failures and zero harness drops.
    Records per-phase per-tenant offered/completed/shed/p95, the
    observed Retry-After ladder, and the budget-floor outcome; `out`
    writes the JSON line to a file as well
    (scripts/tenant_smoke.sh -> BENCH_pr18.json)."""
    import tempfile
    import threading

    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import (EngineFleet, Overloaded, RouterSpec,
                                 ServeSpec, TenantRegistry)
    from singa_tpu.serve.traffic import TrafficGen, steady
    from singa_tpu.utils.checkpoint import CheckpointManager

    vocab, seq = 64, 16
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=seq,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (seq,), "target": (seq,)}})
    params = net.init_params(jax.random.PRNGKey(0))

    ws = tempfile.mkdtemp(prefix="tenant_smoke_")
    mgr = CheckpointManager(ws, log_fn=lambda s: None)
    mgr.save(1, params, {"t": np.zeros(())}, health={"verdict": "ok"})

    # hard partition: each tenant gets 1 of the 2 cb slots and its
    # own queue carve-out, so A flooding ITS queue cannot touch B's
    spec = ServeSpec(buckets=((2, 16),), max_new_tokens=24,
                     batch_window_s=0.002, request_timeout_s=30.0,
                     queue_capacity=8, cb="on", cb_slots=2,
                     cb_block_len=8)
    reg = TenantRegistry.parse(
        "a,queue_frac=0.25,slot_frac=0.5,kv_frac=0.5,budget_floor=4,"
        "brownout_batch_frac=0.125;"
        "b,queue_frac=0.5,slot_frac=0.5,kv_frac=0.5,budget_floor=4")
    fleet = EngineFleet.local(
        net, spec, 1, workspace=ws, params=params,
        router_spec=RouterSpec(probe_period_s=0.05,
                               quarantine_after=3),
        tenancy=reg, log_fn=lambda s: None)
    fleet.start()

    gen = TrafficGen(
        lambda toks, **kw: fleet.generate(toks.tolist(), **kw),
        vocab=vocab, seed=0, log_fn=lambda s: None)
    # quiet: both tenants well inside one engine's capacity; flash:
    # A jumps to ~10x B's rate (>= 5x its own quiet share) while B
    # keeps its quiet cadence
    phases = [steady("quiet", 8.0, 4.0, prompt_lens=(4, 8),
                     tenants=("a", "b"),
                     tenant_weights=(1.0, 1.0)),
              steady("flash", 10.0, 44.0, prompt_lens=(4, 8),
                     tenants=("a", "b"),
                     tenant_weights=(10.0, 1.0))]
    rep = gen.run(phases, drain_timeout_s=30.0)

    # -- Retry-After escalation sub-test ------------------------------
    # A's spec tightened its own batch brownout to 0.125 (shed batch
    # whenever the queue is non-empty), so while interactive fillers
    # keep the queue occupied, A's batch probes shed CONSECUTIVELY —
    # their (tenant, class) streak never resets, and each shed's
    # Retry-After must climb the per-(tenant, class) ladder.
    stop_fill = threading.Event()

    def _fill():
        while not stop_fill.is_set():
            try:
                fleet.generate(list(range(1, 9)), tenant="a",
                               timeout=10.0)
            except Exception:  # noqa: BLE001 — filler sheds are the
                pass           # pressure, not the measurement

    fillers = [threading.Thread(target=_fill, daemon=True)
               for _ in range(8)]
    for t in fillers:
        t.start()
    time.sleep(0.3)                      # let the queue fill
    retry_afters = []
    for _ in range(10):
        try:
            fleet.generate([1, 2, 3, 4], tenant="a",
                           priority="batch", timeout=10.0)
        except Overloaded as e:
            retry_afters.append(float(e.retry_after))
        except Exception:  # noqa: BLE001 — budget stops etc. don't
            pass           # carry a Retry-After; only sheds gate
        time.sleep(0.02)
    stop_fill.set()
    for t in fillers:
        t.join(15.0)
    esc_ratio = (max(retry_afters) / max(min(retry_afters), 1e-9)
                 if len(retry_afters) >= 5 else 0.0)

    # -- budget-floor sub-test ----------------------------------------
    # drain A's budget AND the shared bucket through A, then B must
    # still be able to spend from its guaranteed floor while A is dry
    ba = fleet.tenancy.budget("a")
    bb = fleet.tenancy.budget("b")
    drained = 0
    while ba.spend() and drained < 10_000:
        drained += 1
    b_admitted = bool(bb.spend())
    a_exhausted = not ba.spend()
    fleet.stop()

    tot = rep["totals"]
    quiet = next(r for r in rep["phases"] if r["name"] == "quiet")
    flash = next(r for r in rep["phases"] if r["name"] == "flash")
    qb = quiet["by_tenant"].get("b", {})
    fb = flash["by_tenant"].get("b", {})
    fa = flash["by_tenant"].get("a", {})
    tb = tot["by_tenant"].get("b", {})
    b_p95_ratio = (fb["p95_ms"] / qb["p95_ms"]
                   if fb.get("p95_ms") and qb.get("p95_ms") else 0.0)
    b_completion = (tb.get("completed", 0) / tb["offered"]
                    if tb.get("offered") else 0.0)
    a_vs_b_offered = (fa.get("offered", 0) / fb["offered"]
                      if fb.get("offered") else 0.0)

    gates = {
        "tenant_b_p95_isolated": {
            "value": round(b_p95_ratio, 4), "bound": 1.2,
            "op": "<=", "pass": bool(0.0 < b_p95_ratio <= 1.2)},
        "tenant_b_completion": {
            "value": round(b_completion, 4), "bound": 1.0,
            "op": ">=", "pass": bool(b_completion >= 1.0)},
        "tenant_b_zero_shed": {
            "value": tb.get("shed", 0), "bound": 0, "op": "==",
            "pass": bool(tb.get("shed", 0) == 0)},
        "tenant_a_overloaded": {
            "value": round(a_vs_b_offered, 2), "bound": 5.0,
            "op": ">=", "pass": bool(a_vs_b_offered >= 5.0)},
        "tenant_a_shed_overflow": {
            "value": fa.get("shed", 0), "bound": 1, "op": ">=",
            "pass": bool(fa.get("shed", 0) >= 1)},
        "tenant_a_retry_escalation": {
            "value": round(esc_ratio, 2), "bound": 1.5, "op": ">=",
            "pass": bool(esc_ratio >= 1.5)},
        "budget_floor_b_admitted": {
            "value": int(b_admitted), "bound": 1, "op": "==",
            "pass": bool(b_admitted)},
        "budget_floor_a_exhausted": {
            "value": int(a_exhausted), "bound": 1, "op": "==",
            "pass": bool(a_exhausted)},
        "zero_failures": {
            "value": tot["failed"], "bound": 0, "op": "==",
            "pass": bool(tot["failed"] == 0)},
        "zero_harness_drops": {
            "value": tot["dropped_harness"], "bound": 0, "op": "==",
            "pass": bool(tot["dropped_harness"] == 0)},
    }
    failures = [f"{k}: {g['value']} not {g['op']} {g['bound']}"
                for k, g in gates.items() if not g["pass"]]
    if failures:
        raise RuntimeError("tenant smoke FAILED: "
                           + "; ".join(failures)
                           + f" (errors={tot['errors'][:3]})")

    result = {
        "metric": "tenant_smoke_b_p95_isolation_ratio",
        "value": round(b_p95_ratio, 4),
        "unit": "flash_p95_over_quiet_p95",
        "quiet": {"offered": quiet["offered"],
                  "by_tenant": quiet["by_tenant"]},
        "flash": {"offered": flash["offered"],
                  "by_tenant": flash["by_tenant"]},
        "totals_by_tenant": tot["by_tenant"],
        "retry_afters": [round(r, 4) for r in retry_afters],
        "retry_escalation_ratio": round(esc_ratio, 2),
        "budget_drained_through_a": drained,
        "gates": gates,
        "backend": jax.default_backend(),
    }
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    return result


def main() -> None:
    if "--cpu-baseline" in sys.argv:
        bench_cpu_baseline()
        return
    if "--feed-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_feed_smoke(out=out)))
        return
    if "--serve-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_serve_smoke(out=out)))
        return
    if "--fleet-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_fleet_smoke(out=out)))
        return
    if "--pipeline-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_pipeline_smoke(out=out)))
        return
    if "--cb-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_cb_smoke(out=out)))
        return
    if "--traffic-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_traffic_smoke(out=out)))
        return
    if "--tail-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_tail_smoke(out=out)))
        return
    if "--failover-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_failover_smoke(out=out)))
        return
    if "--transport-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_transport_smoke(out=out)))
        return
    if "--router-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_router_smoke(out=out)))
        return
    if "--trace-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_trace_smoke(out=out)))
        return
    if "--tenant-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_tenant_smoke(out=out)))
        return
    if "--obs-overhead" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_obs_overhead(out=out)))
        return
    if "--perf-smoke" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        print(json.dumps(bench_perf_smoke(out=out)))
        return
    # transformer FIRST: round 3 recorded it at 0.4996 because it ran
    # after the full AlexNet bench on a session-warmed chip; the
    # AlexNet gate carries ~3.6% margin and tolerates second place
    taux = {}
    try:
        t = bench_transformer_mfu()
        taux["transformer_lm_mfu"] = t["value"]
        taux["transformer_tok_sec"] = t["tok_sec"]
        taux["transformer_measured_after_alexnet"] = False
    except Exception as e:
        taux["transformer_lm_mfu_error"] = repr(e)
    primary = bench_alexnet_mfu()
    primary.update(_convergence_aux())
    primary.update(taux)
    try:
        # long-context aux (VERDICT r3 item 2): recorded so the S=4096
        # claim lives in the judged artifact, not just BASELINE.md.
        # Runs LAST — the two gated metrics get the cooler chip.
        # Round 5: D=128 geometry (6x128 heads, the long-context-
        # appropriate head width — BASELINE.md "D=128 prediction
        # measured": D=64's VPU floor caps 12x64 at ~0.42) and the
        # same 50-step windows the gated metrics use.
        lc = bench_transformer_mfu(batch_size=8, seq_len=4096, iters=50,
                                   head_dim=128)
        primary["longctx_s4096_mfu"] = lc["value"]
        primary["longctx_s4096_tok_sec"] = lc["tok_sec"]
        primary["longctx_s4096_geometry"] = "12L 768E 6H D128"
    except Exception as e:
        primary["longctx_s4096_mfu_error"] = repr(e)
    print(json.dumps(primary))
    if "--extra" in sys.argv:
        # transformer MFU is not repeated here: main() already ran it
        # for the primary line's aux keys
        for fn in (bench_lenet, bench_quick_mfu, bench_decode):
            try:
                print(json.dumps(fn()), file=sys.stderr)
            except Exception as e:  # secondary metrics must not break
                print(json.dumps({"metric": fn.__name__,  # the contract
                                  "error": repr(e)}), file=sys.stderr)


if __name__ == "__main__":
    main()
