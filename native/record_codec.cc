// Native Record-proto batch decoder — C++ core for the hot data path.
//
// The reference parses records with generated protobuf C++ inside its
// data/parser layers (layer.cc:646-673 + Record in model.proto:279-305);
// the TPU build's input pipeline needs the same native-speed decode to
// keep the device fed.  This walks the protobuf wire format directly
// (varints + length-delimited fields, schema pinned to
// Record{type=1, image=2} / SingleLabelImageRecord{shape=1, label=2,
// pixel=3, data=4}) and writes a whole batch into caller-provided
// contiguous buffers — one memcpy per record, no per-field Python.
//
// Exposed via ctypes from singa_tpu/data/native.py; the pure-Python
// codec in singa_tpu/data/records.py is the fallback and the oracle.

#include <cstdint>
#include <cstring>

namespace {

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
};

bool read_varint(Cursor* c, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (c->p < c->end && shift < 64) {
    uint8_t b = *c->p++;
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return true;
    }
    shift += 7;
  }
  return false;
}

// Parse one SingleLabelImageRecord submessage.
bool parse_image(const uint8_t* buf, uint64_t len, int64_t* shape,
                 int* ndim, const uint8_t** pixel, uint64_t* pixel_len,
                 int32_t* label) {
  Cursor c{buf, buf + len};
  *ndim = 0;
  *pixel = nullptr;
  *pixel_len = 0;
  *label = 0;
  while (c.p < c.end) {
    uint64_t key;
    if (!read_varint(&c, &key)) return false;
    uint64_t fn = key >> 3, wt = key & 7;
    if (fn == 1 && wt == 0) {               // shape varint
      uint64_t v;
      if (!read_varint(&c, &v)) return false;
      if (*ndim < 4) shape[(*ndim)++] = static_cast<int64_t>(v);
    } else if (fn == 1 && wt == 2) {        // packed shape
      uint64_t ln;
      if (!read_varint(&c, &ln) || ln > uint64_t(c.end - c.p)) return false;
      Cursor pc{c.p, c.p + ln};
      while (pc.p < pc.end) {
        uint64_t v;
        if (!read_varint(&pc, &v)) return false;
        if (*ndim < 4) shape[(*ndim)++] = static_cast<int64_t>(v);
      }
      c.p += ln;
    } else if (fn == 2 && wt == 0) {        // label
      uint64_t v;
      if (!read_varint(&c, &v)) return false;
      *label = static_cast<int32_t>(v);
    } else if (fn == 3 && wt == 2) {        // pixel bytes
      uint64_t ln;
      if (!read_varint(&c, &ln) || ln > uint64_t(c.end - c.p)) return false;
      *pixel = c.p;
      *pixel_len = ln;
      c.p += ln;
    } else {                                // skip unknown field
      if (wt == 0) {
        uint64_t v;
        if (!read_varint(&c, &v)) return false;
      } else if (wt == 2) {
        uint64_t ln;
        if (!read_varint(&c, &ln) || ln > uint64_t(c.end - c.p))
          return false;
        c.p += ln;
      } else if (wt == 5) {
        if (c.end - c.p < 4) return false;
        c.p += 4;
      } else if (wt == 1) {
        if (c.end - c.p < 8) return false;
        c.p += 8;
      } else {
        return false;
      }
    }
  }
  return true;
}

// Locate the image submessage (field 2) of a Record.
bool find_image(const uint8_t* buf, uint64_t len, const uint8_t** img,
                uint64_t* img_len) {
  Cursor c{buf, buf + len};
  *img = nullptr;
  while (c.p < c.end) {
    uint64_t key;
    if (!read_varint(&c, &key)) return false;
    uint64_t fn = key >> 3, wt = key & 7;
    if (fn == 2 && wt == 2) {
      uint64_t ln;
      if (!read_varint(&c, &ln) || ln > uint64_t(c.end - c.p)) return false;
      *img = c.p;
      *img_len = ln;
      return true;
    }
    if (wt == 0) {
      uint64_t v;
      if (!read_varint(&c, &v)) return false;
    } else if (wt == 2) {
      uint64_t ln;
      if (!read_varint(&c, &ln) || ln > uint64_t(c.end - c.p)) return false;
      c.p += ln;
    } else if (wt == 5) {
      if (c.end - c.p < 4) return false;
      c.p += 4;
    } else if (wt == 1) {
      if (c.end - c.p < 8) return false;
      c.p += 8;
    } else {
      return false;
    }
  }
  return false;
}

}  // namespace

extern "C" {

// Shape/label/pixel-size of one serialized Record. Returns 0 on success.
int record_probe(const uint8_t* buf, uint64_t len, int64_t* shape_out,
                 int* ndim_out, uint64_t* pixel_len_out,
                 int32_t* label_out) {
  const uint8_t* img;
  uint64_t img_len;
  if (!find_image(buf, len, &img, &img_len)) return -1;
  const uint8_t* pixel;
  if (!parse_image(img, img_len, shape_out, ndim_out, &pixel,
                   pixel_len_out, label_out))
    return -2;
  return 0;
}

// Decode n records (recs[i], lens[i] — no concatenation needed) into
// pixels_out (n * pixel_len uint8, contiguous) + labels_out (n int32).
// Every record must carry exactly pixel_len pixel bytes AND the same
// shape as (expect_shape, expect_ndim) — same-size different-shape
// records are rejected, not silently reinterpreted. Returns the number
// decoded (== n on success); on the first malformed, wrong-sized, or
// wrong-shaped record i, returns -(i+1).
long record_batch_decode(const uint8_t* const* recs, const uint64_t* lens,
                         long n, const int64_t* expect_shape,
                         int expect_ndim, uint8_t* pixels_out,
                         uint64_t pixel_len, int32_t* labels_out) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* img;
    uint64_t img_len;
    if (!find_image(recs[i], lens[i], &img, &img_len))
      return -(i + 1);
    int64_t shape[4];
    int ndim;
    const uint8_t* pixel;
    uint64_t plen;
    int32_t label;
    if (!parse_image(img, img_len, shape, &ndim, &pixel, &plen, &label))
      return -(i + 1);
    if (plen != pixel_len || pixel == nullptr) return -(i + 1);
    if (ndim != expect_ndim) return -(i + 1);
    for (int d = 0; d < ndim; ++d)
      if (shape[d] != expect_shape[d]) return -(i + 1);
    std::memcpy(pixels_out + static_cast<uint64_t>(i) * pixel_len, pixel,
                pixel_len);
    labels_out[i] = label;
  }
  return n;
}

}  // extern "C"
