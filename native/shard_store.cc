// Native shard record store — C++ core for the hot data path.
//
// Binary-compatible with the reference's Shard format
// (/root/reference/src/utils/shard.cc): tuples of
//   [uint64 keylen][key][uint64 vallen][val]
// in <folder>/shard.dat, with duplicate-key rejection and torn-tail
// truncation on append.  This is the TPU build's native equivalent of
// the reference's C++ shard reader feeding the input pipeline; Python
// binds via ctypes (singa_tpu/data/native.py) with a pure-Python
// fallback when the extension is unavailable.
//
// Build: make -C native   (g++ -O2 -shared -fPIC)

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

struct Reader {
  FILE* f = nullptr;
  std::vector<uint8_t> key_buf;
  std::vector<uint8_t> val_buf;
};

struct Writer {
  FILE* f = nullptr;
  std::unordered_set<std::string> keys;
};

bool read_u64(FILE* f, uint64_t* out) {
  return fread(out, sizeof(uint64_t), 1, f) == 1;
}

// Remaining bytes from the current position — used to bound length
// fields before allocating, so a corrupt header reads as a torn tail
// instead of a std::bad_alloc aborting through the C boundary.
uint64_t bytes_left(FILE* f) {
  long pos = ftell(f);
  fseek(f, 0, SEEK_END);
  long end = ftell(f);
  fseek(f, pos, SEEK_SET);
  return pos < 0 || end < pos ? 0 : static_cast<uint64_t>(end - pos);
}

// Scan for the end of the last complete tuple; fill `keys` if non-null.
long scan_valid_prefix(FILE* f, std::unordered_set<std::string>* keys) {
  long last_ok = 0;
  uint64_t klen, vlen;
  std::vector<char> kbuf;
  for (;;) {
    if (!read_u64(f, &klen)) break;
    if (klen > bytes_left(f)) break;
    kbuf.resize(klen);
    if (klen && fread(kbuf.data(), 1, klen, f) != klen) break;
    if (!read_u64(f, &vlen)) break;
    if (vlen > bytes_left(f)) break;
    if (fseek(f, static_cast<long>(vlen), SEEK_CUR) != 0) break;
    long pos = ftell(f);
    // confirm the value bytes were really present
    fseek(f, 0, SEEK_END);
    long end = ftell(f);
    if (pos > end) break;
    fseek(f, pos, SEEK_SET);
    if (keys) keys->emplace(kbuf.data(), klen);
    last_ok = pos;
  }
  return last_ok;
}

}  // namespace

extern "C" {

// ---------- reader ----------

void* shard_open_read(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  return r;
}

// Returns 1 on success, 0 at EOF/torn tail. Key/val pointers stay valid
// until the next call.
int shard_next(void* handle, const uint8_t** key, uint64_t* klen,
               const uint8_t** val, uint64_t* vlen) {
  auto* r = static_cast<Reader*>(handle);
  uint64_t kl, vl;
  if (!read_u64(r->f, &kl)) return 0;
  if (kl > bytes_left(r->f)) return 0;
  r->key_buf.resize(kl);
  if (kl && fread(r->key_buf.data(), 1, kl, r->f) != kl) return 0;
  if (!read_u64(r->f, &vl)) return 0;
  if (vl > bytes_left(r->f)) return 0;
  r->val_buf.resize(vl);
  if (vl && fread(r->val_buf.data(), 1, vl, r->f) != vl) return 0;
  *key = r->key_buf.data();
  *klen = kl;
  *val = r->val_buf.data();
  *vlen = vl;
  return 1;
}

void shard_seek_first(void* handle) {
  fseek(static_cast<Reader*>(handle)->f, 0, SEEK_SET);
}

long shard_count(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  long pos = ftell(r->f);
  fseek(r->f, 0, SEEK_SET);
  long n = 0;
  uint64_t kl, vl;
  for (;;) {
    if (!read_u64(r->f, &kl)) break;
    if (fseek(r->f, static_cast<long>(kl), SEEK_CUR) != 0) break;
    if (!read_u64(r->f, &vl)) break;
    long want = ftell(r->f) + static_cast<long>(vl);
    fseek(r->f, 0, SEEK_END);
    if (ftell(r->f) < want) break;
    fseek(r->f, want, SEEK_SET);
    ++n;
  }
  fseek(r->f, pos, SEEK_SET);
  return n;
}

void shard_close_read(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  if (r->f) fclose(r->f);
  delete r;
}

// ---------- writer ----------

// mode: 0 = create (truncate), 1 = append (truncate torn tail, load keys)
void* shard_open_write(const char* path, int mode) {
  auto* w = new Writer();
  if (mode == 0) {
    w->f = fopen(path, "wb");
  } else {
    FILE* scan = fopen(path, "rb");
    long last_ok = 0;
    if (scan) {
      last_ok = scan_valid_prefix(scan, &w->keys);
      fclose(scan);
    } else {
      FILE* create = fopen(path, "wb");
      if (create) fclose(create);
    }
    w->f = fopen(path, "r+b");
    if (w->f) {
#ifdef _WIN32
      _chsize(fileno(w->f), last_ok);
#else
      if (ftruncate(fileno(w->f), last_ok) != 0) { /* keep going */ }
#endif
      fseek(w->f, last_ok, SEEK_SET);
    }
  }
  if (!w->f) {
    delete w;
    return nullptr;
  }
  return w;
}

// Returns 1 if inserted, 0 if duplicate key or empty value.
int shard_insert(void* handle, const uint8_t* key, uint64_t klen,
                 const uint8_t* val, uint64_t vlen) {
  auto* w = static_cast<Writer*>(handle);
  if (vlen == 0) return 0;
  std::string k(reinterpret_cast<const char*>(key), klen);
  if (!w->keys.insert(k).second) return 0;
  fwrite(&klen, sizeof(uint64_t), 1, w->f);
  fwrite(key, 1, klen, w->f);
  fwrite(&vlen, sizeof(uint64_t), 1, w->f);
  fwrite(val, 1, vlen, w->f);
  return 1;
}

void shard_flush(void* handle) { fflush(static_cast<Writer*>(handle)->f); }

void shard_close_write(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  if (w->f) fclose(w->f);
  delete w;
}

}  // extern "C"
