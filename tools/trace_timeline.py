#!/usr/bin/env python
"""Render a merged fleet trace (obs/collect.py output, or any single
process's Chrome-trace JSON) as a per-request text timeline with
critical-path attribution — the post-mortem read when no Perfetto UI
is at hand.

    python tools/trace_timeline.py trace.json [--trace ID] [--top N]

Without `--trace` every trace id in the file is listed (span count +
end-to-end span) and the LAST one is rendered.  The timeline section
shows the span tree in timestamp order with process/engine tags; the
attribution section ranks spans by SELF time (duration minus child
overlap, `collect.critical_path`) — the head of that list is where
the request's wall-clock actually went.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from singa_tpu.obs import collect  # noqa: E402


def _fmt_us(us: float) -> str:
    if us >= 1e6:
        return f"{us / 1e6:.3f}s"
    if us >= 1e3:
        return f"{us / 1e3:.3f}ms"
    return f"{us:.0f}us"


def render(merged, trace_id: str, top: int = 10) -> str:
    spans = collect.spans_of(merged, trace_id)
    if not spans:
        return f"trace {trace_id}: no spans"
    processes = merged.get("processes", {})
    by_id = {e["args"]["span_id"]: e for e in spans}
    t0 = min(e["ts"] for e in spans)
    t1 = max(e["ts"] + e.get("dur", 0.0) for e in spans)

    def depth(e):
        d, seen = 0, set()
        while True:
            pid = e["args"].get("parent_id")
            parent = by_id.get(pid)
            if parent is None or pid in seen:
                return d
            seen.add(pid)
            d, e = d + 1, parent
    lines = [f"trace {trace_id}: {len(spans)} span(s), "
             f"{_fmt_us(t1 - t0)} end to end"]
    orphan_ids = {e["args"]["span_id"]
                  for e in collect.orphans(merged, trace_id)}
    if orphan_ids:
        lines.append(f"  WARNING: {len(orphan_ids)} orphan span(s) "
                     f"(parent not in file)")
    lines.append("")
    lines.append("timeline:")
    for e in spans:
        a = e["args"]
        tags = [processes.get(e.get("pid"), str(e.get("pid")))]
        if a.get("engine"):
            tags.append(str(a["engine"]))
        if a.get("corr"):
            tags.append(str(a["corr"]))
        flag = " ORPHAN" if a["span_id"] in orphan_ids else ""
        lines.append(
            f"  +{_fmt_us(e['ts'] - t0):>10} "
            f"{'  ' * depth(e)}{e['name']} "
            f"[{_fmt_us(e.get('dur', 0.0))}] "
            f"({', '.join(tags)}){flag}")
    lines.append("")
    lines.append(f"critical path (self time, top {top}):")
    total = max(t1 - t0, 1e-9)
    for row in collect.critical_path(merged, trace_id)[:top]:
        where = row["process"] + (f"/{row['engine']}"
                                  if row.get("engine") else "")
        lines.append(
            f"  {_fmt_us(row['self_us']):>10} "
            f"{100.0 * row['self_us'] / total:5.1f}%  "
            f"{row['name']} ({where})")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="text timeline + critical path for one trace id "
                    "in a merged fleet trace")
    ap.add_argument("path", help="merged trace JSON "
                                 "(obs/collect.py output)")
    ap.add_argument("--trace", default=None,
                    help="trace id to render (default: list all, "
                         "render the last)")
    ap.add_argument("--top", type=int, default=10,
                    help="critical-path rows to show")
    args = ap.parse_args(argv)
    with open(args.path) as f:
        merged = json.load(f)
    ids = collect.trace_ids(merged)
    if not ids:
        print("no spans with trace ids in this file")
        return 1
    if args.trace is None:
        print(f"{len(ids)} trace id(s) in {args.path}:")
        for t in ids:
            s = collect.spans_of(merged, t)
            t0 = min(e["ts"] for e in s)
            t1 = max(e["ts"] + e.get("dur", 0.0) for e in s)
            print(f"  {t}  {len(s):>4} span(s)  {_fmt_us(t1 - t0)}")
        print()
        args.trace = ids[-1]
    elif args.trace not in ids:
        print(f"trace {args.trace!r} not in this file "
              f"(have: {', '.join(ids)})")
        return 1
    print(render(merged, args.trace, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
