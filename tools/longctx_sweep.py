"""Long-context flash block-geometry sweep, in-net and in-process.

Round-3 tuned the packed flash kernel only at S=1024/D=64; at S=4096+
attention grows to ~half the model FLOPs and the net MFU slid to
0.375/0.317.  This sweeps (block_q, block_k) at the long sequence
lengths ON THE TRAIN STEP (not a standalone microbench — those get
const-hoisted or measure the wrong layout), same-process so chip drift
cancels.

    python tools/longctx_sweep.py [--seq 4096] [--batch 8] [--iters 10]
        [--reps 3] [--blocks 512x512,1024x512,...]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def measure(seq_len, batch, iters, reps, bq, bk, split=False,
            head_dim=64):
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)
    from singa_tpu.ops import attention
    from singa_tpu.utils.flops import mfu, net_train_flops
    from singa_tpu.utils.profiler import hard_sync

    attention.set_flash_blocks((bq, bk))
    prev_split = attention.MASK_SPLIT
    attention.MASK_SPLIT = split
    try:
        # heads scale inversely with head_dim so every sweep point keeps
        # the same 768-wide attention (12x64 default, 6x128 for the
        # D=128 floor-proof measurement)
        if 768 % head_dim:
            raise ValueError(f"--head_dim must divide 768, got {head_dim}")
        cfg = transformer_lm(vocab_size=32768, num_layers=12,
                             embed_dim=768, num_heads=768 // head_dim,
                             head_dim=head_dim,
                             seq_len=seq_len, batchsize=batch)
        cfg.precision = "bfloat16"
        trainer = Trainer(cfg, {"data": {"input": (seq_len,),
                                         "target": (seq_len,)}},
                          log_fn=lambda s: None)
        params, opt = trainer.init(seed=0)
        bt = next(synthetic_token_batches(batch, seq_len, 32768))
        bt = jax.tree_util.tree_map(jax.device_put, bt)
        key = jax.random.PRNGKey(0)
        params, opt, _ = trainer.train_steps(params, opt, bt, 0, key,
                                             iters)
        hard_sync(params)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            params, opt, _ = trainer.train_steps(params, opt, bt, iters,
                                                 key, iters)
            hard_sync(params)
            best = min(best, (time.perf_counter() - t0) / iters)
        flops = net_train_flops(trainer.train_net)
        return best, mfu(flops, best), flops
    finally:
        attention.set_flash_blocks(None)
        attention.MASK_SPLIT = prev_split


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--blocks", default="512x512,1024x512,512x1024,"
                                        "1024x1024,2048x512,256x512")
    ap.add_argument("--head_dim", type=int, default=64)
    args = ap.parse_args()
    batch = args.batch or max(32 * 1024 // args.seq, 1)
    print(f"# S={args.seq} batch={batch} head_dim={args.head_dim} "
          f"iters={args.iters} reps={args.reps} (best-of)")
    base = None
    for spec in args.blocks.split(","):
        # production runs MASK_SPLIT=False (BASELINE: -55% at 512x1024);
        # ':split' opts a sweep point into the A/B variant
        split = spec.endswith(":split")
        bq, bk = (int(x) for x in spec.split(":")[0].split("x"))
        tag = " split" if split else ""
        try:
            step, util, flops = measure(args.seq, batch, args.iters,
                                        args.reps, bq, bk, split,
                                        args.head_dim)
        except Exception as e:
            print(f"bq={bq:5d} bk={bk:5d}{tag}  FAILED: "
                  f"{type(e).__name__}: {str(e)[:110]}", flush=True)
            continue
        base = base or step
        print(f"bq={bq:5d} bk={bk:5d}{tag}  {step * 1e3:8.2f} ms/step  "
              f"MFU {util:.4f}  ({(step - base) / base * 100:+.1f}% vs "
              f"first)", flush=True)


if __name__ == "__main__":
    main()
