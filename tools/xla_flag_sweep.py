"""Sweep TPU compiler options over the AlexNet gate workload.

Env XLA_FLAGS cannot carry xla_tpu_* flags here (the client-side parser
rejects flags outside its registry and aborts), but
`jit(...).lower(...).compile(compiler_options=...)` travels the proto
path that the axon compile helper forwards per-compile — this is the
mechanism Trainer.TPU_CONV_COMPILER_OPTIONS uses in production.

Measured on a v5e chip (2026-07-30), best of 3-4 windows, AlexNet-full
batch 8192 (run-to-run AND compile-to-compile variance ~±1.5%):

    default (16MB scoped vmem)                      135-136 ms
    xla_tpu_scoped_vmem_limit_kib=98304             127-129 ms  <- adopted
    xla_tpu_scoped_vmem_limit_kib=131072            2811 ms (spills!)
    + xla_tpu_rwb_fusion=false                      127-129 ms (noise)
    + xla_tpu_enable_latency_hiding_scheduler=true  128 ms (noise)
    + xla_tpu_enable_experimental_fusion_cost_model 135 ms (worse)
    + xla_tpu_enable_dot_strength_reduction=false   131 ms (worse)

Usage: python tools/xla_flag_sweep.py  [--batch 8192]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OPTION_SETS = [
    ("default", None),
    ("vmem96m", {"xla_tpu_scoped_vmem_limit_kib": "98304"}),
    ("vmem96m+rwb-off", {"xla_tpu_scoped_vmem_limit_kib": "98304",
                         "xla_tpu_rwb_fusion": "false"}),
    ("vmem96m+latency-sched",
     {"xla_tpu_scoped_vmem_limit_kib": "98304",
      "xla_tpu_enable_latency_hiding_scheduler": "true"}),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8192)
    args = ap.parse_args()

    import numpy as np
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.vision import alexnet_cifar10_full
    from singa_tpu.utils.flops import net_train_flops, peak_flops
    from singa_tpu.utils.profiler import hard_sync

    cfg = alexnet_cifar10_full(batchsize=args.batch)
    cfg.precision = "bfloat16"
    # strip the production default so the 'default' row is a REAL
    # baseline (jit-level compiler options merge into every
    # lowered.compile(), so they must not be baked into the jit here)
    Trainer.TPU_CONV_COMPILER_OPTIONS = {}
    tr = Trainer(cfg, {"data": {"pixel": (3, 32, 32), "label": ()}},
                 log_fn=lambda s: None, donate=False)
    params, opt = tr.init(seed=0)
    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jax.device_put(rng.standard_normal(
            (args.batch, 3, 32, 32)).astype(np.float32)),
        "label": jax.device_put(
            rng.integers(0, 10, (args.batch,)).astype(np.int32))}}
    key = jax.random.PRNGKey(0)
    lowered = tr.train_steps.lower(params, opt, batch, 0, key, 10)
    flops = net_train_flops(tr.train_net)
    peak = peak_flops() or float("nan")
    for name, opts in OPTION_SETS:
        try:
            comp = (lowered.compile(compiler_options=opts) if opts
                    else lowered.compile())
            p, o = params, opt
            p, o, _ = comp(p, o, batch, 0, key)
            hard_sync(p)
            best = 1e9
            for _ in range(4):
                t0 = time.perf_counter()
                p, o, _ = comp(p, o, batch, 10, key)
                hard_sync(p)
                best = min(best, (time.perf_counter() - t0) / 10)
            print(f"{name:24s} step {best*1e3:8.2f} ms  "
                  f"MFU {flops/(best*peak):.4f}", flush=True)
        except Exception as e:
            print(f"{name:24s} FAIL {str(e)[:140]}", flush=True)


if __name__ == "__main__":
    main()
