#!/usr/bin/env python
"""Tabulate the per-PR bench artifacts (BENCH_pr*.json) into one
markdown table, sorted by PR number.

Each smoke bench writes a single JSON line whose shape is its own
(MFU numbers, fleet latencies, autoscaler outcomes, ...), so the
table keeps the stable triple every artifact shares — metric, value,
unit — and compresses the rest into a highlights column drawn from a
fixed key list.  Unreadable or malformed artifacts get an error row
instead of being skipped: a report that silently drops a PR reads as
"that PR had no numbers".

Artifacts listed in REQUIRED_GATES must additionally carry a `gates`
dict covering every named gate, all passing; a listed artifact that is
present but missing gates — or recording a failed one — makes the
report exit non-zero.  A green table over a gateless artifact reads as
"the acceptance bar held" when nothing was checked.

`--trajectory` turns the so-far-unused bench trajectory into a gate:
one markdown table of tracked metrics across every BENCH_pr*.json in
PR order, with per-PR deltas against the previous artifact that
carried the same key.  A tracked key that degrades past its tolerance
(TREND_TOL — a loose order-of-magnitude guard, since adjacent PRs
bench different workloads), ANY artifact recording a failed gate, and
ANY unreadable artifact all exit non-zero in this mode.

Usage: python tools/bench_report.py [--trajectory] [repo_root]
Exit status: 0 unless a REQUIRED_GATES artifact is present with
missing or failing gates (plus the stricter trajectory failures
above when --trajectory is given).
"""

import glob
import json
import os
import re
import sys

# shown (when present) in the highlights column, in this order
HIGHLIGHT_KEYS = (
    "p50_latency_ms", "p95_latency_ms", "p95_ms", "shed_rate",
    "kill_recovery_s", "canaries", "promotions", "rollbacks",
    "engines_peak", "engines_final", "scale_ups", "scale_downs",
    "stream_drained", "hedge_rate", "retry_amplification",
    "interactive_p95_ms", "expired_on_arrival", "tok_sec", "qps",
    "completed", "backend",
)

# artifact -> gate names its `gates` dict must record as passing.
# Absent artifacts are fine (older checkouts); present-but-gateless is
# an error.
REQUIRED_GATES = {
    "BENCH_pr12.json": (
        "tail_ratio", "hedge_rate", "retry_amplification",
        "interactive_p95", "best_effort_sheds", "expired_on_arrival",
        "doa_zero_steps",
    ),
    "BENCH_pr13.json": (
        "failover_stream_failures", "failover_dup_tokens",
        "failover_missing_tokens", "failover_spliced_streams",
        "failover_parity_mismatch", "resume_fault_terminal",
        "resume_fault_dup_tokens", "idle_watchdog_resumed",
    ),
    "BENCH_pr14.json": (
        "trace_ids_per_request", "trace_resume_in_trace",
        "trace_hedge_legs", "trace_engines_spanned",
        "trace_orphan_spans", "stage_attribution_err",
        "flightrec_replayed", "trace_overhead",
    ),
    "BENCH_pr15.json": (
        "warmup_cb_compiles", "post_warmup_compiles",
        "recompile_anomalies", "restart_to_serving",
        "restart_to_training", "hbm_watermark",
        "costwatch_compiles", "obs_overhead", "trajectory_renders",
    ),
    "BENCH_pr18.json": (
        "tenant_b_p95_isolated", "tenant_b_completion",
        "tenant_b_zero_shed", "tenant_a_overloaded",
        "tenant_a_shed_overflow", "tenant_a_retry_escalation",
        "budget_floor_b_admitted", "budget_floor_a_exhausted",
        "zero_failures", "zero_harness_drops",
    ),
    "BENCH_pr19.json": (
        "restart_stream_failures", "restart_dup_tokens",
        "restart_missing_tokens", "restart_parity_mismatch",
        "restart_recovered_streams", "handoff_client_failures",
        "handoff_refusal_points_successor", "handoff_parity_mismatch",
        "state_quarantine_survived", "state_shed_streak_survived",
        "wal_overhead_ratio", "wal_fault_counted_loss",
    ),
    "BENCH_pr20.json": (
        "transport_p50_improved", "transport_ser_time_reduced",
        "transport_stream_parity", "wire_splice_exactly_once",
        "wire_fuzz_no_hangs", "wire_fault_absorbed",
    ),
}

# --trajectory: tracked keys -> (direction, tolerance factor).  The
# comparison is consecutive-occurrence across PR artifacts, which mixes
# workloads (pr5's serve smoke vs pr7's fleet smoke both report
# p95_latency_ms), so the tolerance is a loose multiplicative guard
# against order-of-magnitude regressions, not cross-workload noise.
TREND_TOL = {
    "p50_latency_ms": ("lower", 3.0),
    "p95_latency_ms": ("lower", 3.0),
    "p95_ms": ("lower", 3.0),
    "p99_ms": ("lower", 3.0),
    "interactive_p95_ms": ("lower", 3.0),
    "tok_sec": ("higher", 3.0),
    "qps": ("higher", 3.0),
    "hedge_rate": ("lower", 3.0),
    "retry_amplification": ("lower", 2.0),
    "shed_rate": ("lower", 3.0),
    "obs_overhead": ("lower", None),        # shown, never gated: a
    "trace_overhead": ("lower", None),      # near-zero base makes any
    "restart_to_serving_s": ("lower", None),  # ratio meaningless
    "restart_to_training_s": ("lower", None),
    "hbm_watermark_bytes": ("lower", 4.0),
    "mfu": ("higher", 3.0),
    "transport_p50_ms": ("lower", 3.0),     # binary-path unary p50
    "binary_ser_us": ("lower", 3.0),        # per-stream wire encode
}


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _check_gates(name, d):
    """Return a list of gate problems for artifact `name` (empty when
    the artifact is not listed in REQUIRED_GATES or all gates pass)."""
    required = REQUIRED_GATES.get(name)
    if not required:
        return []
    gates = d.get("gates")
    if not isinstance(gates, dict):
        return [f"{name}: no `gates` dict recorded"]
    problems = []
    for g in required:
        rec = gates.get(g)
        if not isinstance(rec, dict):
            problems.append(f"{name}: gate `{g}` missing")
        elif not rec.get("pass"):
            problems.append(
                f"{name}: gate `{g}` FAILED "
                f"({_fmt(rec.get('value'))} not {rec.get('op', '?')} "
                f"{_fmt(rec.get('bound'))})")
    return problems


def _gate_summary(name, d):
    """One highlights token summarising the recorded gates."""
    gates = d.get("gates")
    if not isinstance(gates, dict) or not gates:
        return None
    passed = sum(1 for g in gates.values()
                 if isinstance(g, dict) and g.get("pass"))
    return f"gates={passed}/{len(gates)}"


def _row(path, problems):
    name = os.path.basename(path)
    m = re.search(r"BENCH_pr(\d+)\.json$", name)
    pr = int(m.group(1)) if m else -1
    try:
        with open(path) as f:
            d = json.loads(f.readline())
    except (OSError, ValueError) as e:
        if name in REQUIRED_GATES:
            problems.append(f"{name}: unreadable, gates unverifiable")
        return (pr, name, "(unreadable)", "-", "-",
                f"{type(e).__name__}: {e}")
    problems.extend(_check_gates(name, d))
    hi_parts = [f"{k}={_fmt(d[k])}" for k in HIGHLIGHT_KEYS
                if d.get(k) is not None]
    gs = _gate_summary(name, d)
    if gs:
        hi_parts.append(gs)
    return (pr, name, str(d.get("metric", "?")),
            _fmt(d.get("value", "?")), str(d.get("unit", "?")),
            "; ".join(hi_parts))


def report(root=".", problems=None) -> str:
    if problems is None:
        problems = []
    paths = glob.glob(os.path.join(root, "BENCH_pr*.json"))
    rows = sorted(_row(p, problems) for p in paths)
    lines = ["| PR | artifact | metric | value | unit | highlights |",
             "|---:|----------|--------|------:|------|------------|"]
    for pr, name, metric, value, unit, hi in rows:
        lines.append(f"| {pr} | {name} | {metric} | {value} | {unit} "
                     f"| {hi} |")
    if not rows:
        lines.append("| - | (no BENCH_pr*.json found) | | | | |")
    return "\n".join(lines)


def _tracked(d):
    """{tracked_key: value} for one artifact: top-level keys named in
    TREND_TOL, one-level-nested dict keys (`cb.p95_ms` tracks as
    `p95_ms` only when the top level has none), and the artifact's
    headline `value` filed under its `metric` name when that name is
    tracked (pr6's obs_overhead artifact)."""
    out = {}
    metric = d.get("metric")
    if metric in TREND_TOL and isinstance(d.get("value"), (int, float)):
        out[metric] = float(d["value"])
    for k, v in d.items():
        if k in TREND_TOL and isinstance(v, (int, float)):
            out[k] = float(v)
    for k, v in d.items():
        if isinstance(v, dict):
            for kk, vv in v.items():
                if (kk in TREND_TOL and kk not in out
                        and isinstance(vv, (int, float))):
                    out[kk] = float(vv)
    return out


def _regressed(key, prev, cur):
    """The failure string when cur is past tolerance vs prev, else
    None.  Keys with a None factor are reported but never gated."""
    direction, factor = TREND_TOL[key]
    if factor is None or prev is None:
        return None
    if direction == "lower" and prev > 0 and cur > prev * factor:
        return (f"`{key}` regressed {cur / prev:.2f}x "
                f"({_fmt(prev)} -> {_fmt(cur)}, tolerance {factor}x)")
    if direction == "higher" and cur > 0 and prev > cur * factor:
        return (f"`{key}` regressed {prev / cur:.2f}x "
                f"({_fmt(prev)} -> {_fmt(cur)}, tolerance {factor}x)")
    return None


def trajectory(root=".", problems=None) -> str:
    """Per-PR trajectory table over every BENCH_pr*.json; see module
    docstring for what lands in `problems`."""
    if problems is None:
        problems = []
    arts = []
    for path in glob.glob(os.path.join(root, "BENCH_pr*.json")):
        name = os.path.basename(path)
        m = re.search(r"BENCH_pr(\d+)\.json$", name)
        pr = int(m.group(1)) if m else -1
        try:
            with open(path) as f:
                d = json.loads(f.readline())
        except (OSError, ValueError) as e:
            problems.append(f"{name}: unreadable "
                            f"({type(e).__name__}: {e})")
            continue
        arts.append((pr, name, d))
    arts.sort()
    lines = ["| PR | artifact | metric | value | trend (Δ vs last "
             "carrier) | gates |",
             "|---:|----------|--------|------:|---------"
             "|-------|"]
    last_seen = {}                   # key -> (pr, value)
    for pr, name, d in arts:
        problems.extend(_check_gates(name, d))
        gates = d.get("gates")
        if isinstance(gates, dict):
            for g, rec in gates.items():
                if isinstance(rec, dict) and not rec.get("pass"):
                    problems.append(f"{name}: gate `{g}` FAILED "
                                    f"({_fmt(rec.get('value'))} not "
                                    f"{rec.get('op', '?')} "
                                    f"{_fmt(rec.get('bound'))})")
        cells = []
        for key, val in sorted(_tracked(d).items()):
            prev = last_seen.get(key)
            delta = ""
            if prev is not None and prev[1]:
                pct = (val - prev[1]) / abs(prev[1]) * 100.0
                delta = f" ({pct:+.0f}% vs pr{prev[0]})"
                bad = _regressed(key, prev[1], val)
                if bad:
                    problems.append(f"{name}: {bad}")
            cells.append(f"{key}={_fmt(val)}{delta}")
            last_seen[key] = (pr, val)
        gs = _gate_summary(name, d) or ""
        lines.append(f"| {pr} | {name} | {d.get('metric', '?')} "
                     f"| {_fmt(d.get('value', '?'))} "
                     f"| {'; '.join(cells)} | {gs} |")
    if len(lines) == 2:
        lines.append("| - | (no BENCH_pr*.json found) | | | | |")
    return "\n".join(lines)


def main(argv):
    args = [a for a in argv[1:] if a != "--trajectory"]
    problems = []
    root = args[0] if args else "."
    if "--trajectory" in argv:
        print(trajectory(root, problems))
    else:
        print(report(root, problems))
    if problems:
        for p in problems:
            print(f"GATE PROBLEM: {p}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
