#!/usr/bin/env python
"""Tabulate the per-PR bench artifacts (BENCH_pr*.json) into one
markdown table, sorted by PR number.

Each smoke bench writes a single JSON line whose shape is its own
(MFU numbers, fleet latencies, autoscaler outcomes, ...), so the
table keeps the stable triple every artifact shares — metric, value,
unit — and compresses the rest into a highlights column drawn from a
fixed key list.  Unreadable or malformed artifacts get an error row
instead of being skipped: a report that silently drops a PR reads as
"that PR had no numbers".

Usage: python tools/bench_report.py [repo_root]
"""

import glob
import json
import os
import re
import sys

# shown (when present) in the highlights column, in this order
HIGHLIGHT_KEYS = (
    "p50_latency_ms", "p95_latency_ms", "p95_ms", "shed_rate",
    "kill_recovery_s", "canaries", "promotions", "rollbacks",
    "engines_peak", "engines_final", "scale_ups", "scale_downs",
    "stream_drained", "tok_sec", "qps", "completed", "backend",
)


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _row(path):
    name = os.path.basename(path)
    m = re.search(r"BENCH_pr(\d+)\.json$", name)
    pr = int(m.group(1)) if m else -1
    try:
        with open(path) as f:
            d = json.loads(f.readline())
    except (OSError, ValueError) as e:
        return (pr, name, "(unreadable)", "-", "-",
                f"{type(e).__name__}: {e}")
    hi = "; ".join(f"{k}={_fmt(d[k])}" for k in HIGHLIGHT_KEYS
                   if d.get(k) is not None)
    return (pr, name, str(d.get("metric", "?")),
            _fmt(d.get("value", "?")), str(d.get("unit", "?")), hi)


def report(root=".") -> str:
    paths = glob.glob(os.path.join(root, "BENCH_pr*.json"))
    rows = sorted(_row(p) for p in paths)
    lines = ["| PR | artifact | metric | value | unit | highlights |",
             "|---:|----------|--------|------:|------|------------|"]
    for pr, name, metric, value, unit, hi in rows:
        lines.append(f"| {pr} | {name} | {metric} | {value} | {unit} "
                     f"| {hi} |")
    if not rows:
        lines.append("| - | (no BENCH_pr*.json found) | | | | |")
    return "\n".join(lines)


if __name__ == "__main__":
    print(report(sys.argv[1] if len(sys.argv) > 1 else "."))
