#!/usr/bin/env python
"""Tabulate the per-PR bench artifacts (BENCH_pr*.json) into one
markdown table, sorted by PR number.

Each smoke bench writes a single JSON line whose shape is its own
(MFU numbers, fleet latencies, autoscaler outcomes, ...), so the
table keeps the stable triple every artifact shares — metric, value,
unit — and compresses the rest into a highlights column drawn from a
fixed key list.  Unreadable or malformed artifacts get an error row
instead of being skipped: a report that silently drops a PR reads as
"that PR had no numbers".

Artifacts listed in REQUIRED_GATES must additionally carry a `gates`
dict covering every named gate, all passing; a listed artifact that is
present but missing gates — or recording a failed one — makes the
report exit non-zero.  A green table over a gateless artifact reads as
"the acceptance bar held" when nothing was checked.

Usage: python tools/bench_report.py [repo_root]
Exit status: 0 unless a REQUIRED_GATES artifact is present with
missing or failing gates.
"""

import glob
import json
import os
import re
import sys

# shown (when present) in the highlights column, in this order
HIGHLIGHT_KEYS = (
    "p50_latency_ms", "p95_latency_ms", "p95_ms", "shed_rate",
    "kill_recovery_s", "canaries", "promotions", "rollbacks",
    "engines_peak", "engines_final", "scale_ups", "scale_downs",
    "stream_drained", "hedge_rate", "retry_amplification",
    "interactive_p95_ms", "expired_on_arrival", "tok_sec", "qps",
    "completed", "backend",
)

# artifact -> gate names its `gates` dict must record as passing.
# Absent artifacts are fine (older checkouts); present-but-gateless is
# an error.
REQUIRED_GATES = {
    "BENCH_pr12.json": (
        "tail_ratio", "hedge_rate", "retry_amplification",
        "interactive_p95", "best_effort_sheds", "expired_on_arrival",
        "doa_zero_steps",
    ),
    "BENCH_pr13.json": (
        "failover_stream_failures", "failover_dup_tokens",
        "failover_missing_tokens", "failover_spliced_streams",
        "failover_parity_mismatch", "resume_fault_terminal",
        "resume_fault_dup_tokens", "idle_watchdog_resumed",
    ),
    "BENCH_pr14.json": (
        "trace_ids_per_request", "trace_resume_in_trace",
        "trace_hedge_legs", "trace_engines_spanned",
        "trace_orphan_spans", "stage_attribution_err",
        "flightrec_replayed", "trace_overhead",
    ),
}


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _check_gates(name, d):
    """Return a list of gate problems for artifact `name` (empty when
    the artifact is not listed in REQUIRED_GATES or all gates pass)."""
    required = REQUIRED_GATES.get(name)
    if not required:
        return []
    gates = d.get("gates")
    if not isinstance(gates, dict):
        return [f"{name}: no `gates` dict recorded"]
    problems = []
    for g in required:
        rec = gates.get(g)
        if not isinstance(rec, dict):
            problems.append(f"{name}: gate `{g}` missing")
        elif not rec.get("pass"):
            problems.append(
                f"{name}: gate `{g}` FAILED "
                f"({_fmt(rec.get('value'))} not {rec.get('op', '?')} "
                f"{_fmt(rec.get('bound'))})")
    return problems


def _gate_summary(name, d):
    """One highlights token summarising the recorded gates."""
    gates = d.get("gates")
    if not isinstance(gates, dict) or not gates:
        return None
    passed = sum(1 for g in gates.values()
                 if isinstance(g, dict) and g.get("pass"))
    return f"gates={passed}/{len(gates)}"


def _row(path, problems):
    name = os.path.basename(path)
    m = re.search(r"BENCH_pr(\d+)\.json$", name)
    pr = int(m.group(1)) if m else -1
    try:
        with open(path) as f:
            d = json.loads(f.readline())
    except (OSError, ValueError) as e:
        if name in REQUIRED_GATES:
            problems.append(f"{name}: unreadable, gates unverifiable")
        return (pr, name, "(unreadable)", "-", "-",
                f"{type(e).__name__}: {e}")
    problems.extend(_check_gates(name, d))
    hi_parts = [f"{k}={_fmt(d[k])}" for k in HIGHLIGHT_KEYS
                if d.get(k) is not None]
    gs = _gate_summary(name, d)
    if gs:
        hi_parts.append(gs)
    return (pr, name, str(d.get("metric", "?")),
            _fmt(d.get("value", "?")), str(d.get("unit", "?")),
            "; ".join(hi_parts))


def report(root=".", problems=None) -> str:
    if problems is None:
        problems = []
    paths = glob.glob(os.path.join(root, "BENCH_pr*.json"))
    rows = sorted(_row(p, problems) for p in paths)
    lines = ["| PR | artifact | metric | value | unit | highlights |",
             "|---:|----------|--------|------:|------|------------|"]
    for pr, name, metric, value, unit, hi in rows:
        lines.append(f"| {pr} | {name} | {metric} | {value} | {unit} "
                     f"| {hi} |")
    if not rows:
        lines.append("| - | (no BENCH_pr*.json found) | | | | |")
    return "\n".join(lines)


def main(argv):
    problems = []
    print(report(argv[1] if len(argv) > 1 else ".", problems))
    if problems:
        for p in problems:
            print(f"GATE PROBLEM: {p}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
