#!/usr/bin/env python
"""Validate / dump a router session WAL offline.

After a crash (or before promoting a standby) an operator wants to
know what the journal actually holds: which epoch wrote it, whether
the tail is torn (normal after SIGKILL — replay truncates, never
poisons), how many sessions are live vs closed, and which streams a
successor would re-admit.  This wraps `serve.sessionlog.walcheck`
over a WAL file, a `<ws>/router/` directory (newest journal), or a
workspace root.

Usage:
    python tools/walcheck.py <wal-file | router-dir | workspace>
    python tools/walcheck.py --records <wal-file>    # dump every
                                                     # decoded record

Exit status: 0 on a readable journal (torn tail included — that is a
survivable state, not an error), 1 when no journal is found or the
header itself is unreadable.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from singa_tpu.serve.sessionlog import (read_epoch, replay_wal,  # noqa: E402
                                        walcheck)


def _resolve(path: str):
    """A WAL file, a router dir, or a workspace containing one."""
    if os.path.isfile(path):
        return path
    for d in (path, os.path.join(path, "router")):
        if not os.path.isdir(d):
            continue
        wals = sorted(f for f in os.listdir(d)
                      if f.startswith("wal-") and f.endswith(".ndjson"))
        if wals:
            return os.path.join(d, wals[-1])
    return None


def main(argv) -> int:
    args = [a for a in argv if not a.startswith("--")]
    dump_records = "--records" in argv
    if len(args) != 1:
        print(__doc__.strip(), file=sys.stderr)
        return 1
    wal = _resolve(args[0])
    if wal is None:
        print(f"walcheck: no wal-*.ndjson under {args[0]!r}",
              file=sys.stderr)
        return 1
    summary = walcheck(wal)
    d = os.path.dirname(wal)
    summary["dir_epoch"] = read_epoch(d)
    if summary["epoch"] is not None and \
            summary["dir_epoch"] > summary["epoch"]:
        summary["fenced"] = True      # a successor has claimed over
    print(json.dumps(summary, indent=2))
    if dump_records:
        _, records, _ = replay_wal(wal)
        for r in records:
            print(json.dumps(r))
    return 0 if summary.get("epoch") is not None else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
