"""In-process A/B: packed flash path direct (mesh=None) vs through the
round-5 shard_map wrapper (1-device mesh) on the bench transformer
stack.  Proves un-fencing the packed kernels for mesh runs costs
nothing at mesh=1 — the same kernel, same layout, one shard_map
boundary added.  Chip drift cancels in-process (best-of scan windows,
same rules as bench.py).

    python tools/packed_mesh_ab.py [--seq 1024] [--batch 32]
        [--iters 30] [--reps 3] [--kv_heads 0 (=heads)]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def measure(seq_len, batch, iters, reps, kv_heads, use_mesh):
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)
    from singa_tpu.parallel import make_mesh
    from singa_tpu.utils.flops import mfu, net_train_flops
    from singa_tpu.utils.profiler import hard_sync

    mesh = make_mesh(jax.devices()[:1]) if use_mesh else None
    cfg = transformer_lm(vocab_size=32768, num_layers=12, embed_dim=768,
                         num_heads=12, head_dim=64, seq_len=seq_len,
                         batchsize=batch,
                         num_kv_heads=kv_heads or None)
    cfg.precision = "bfloat16"
    trainer = Trainer(cfg, {"data": {"input": (seq_len,),
                                     "target": (seq_len,)}},
                      log_fn=lambda s: None, mesh=mesh)
    params, opt = trainer.init(seed=0)
    bt = next(synthetic_token_batches(batch, seq_len, 32768))
    bt = jax.tree_util.tree_map(jax.device_put, bt)
    key = jax.random.PRNGKey(0)
    params, opt, _ = trainer.train_steps(params, opt, bt, 0, key, iters)
    hard_sync(params)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        params, opt, _ = trainer.train_steps(params, opt, bt, iters, key,
                                             iters)
        hard_sync(params)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best, mfu(net_train_flops(trainer.train_net), best)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--kv_heads", type=int, default=0)
    args = ap.parse_args()
    print(f"# S={args.seq} batch={args.batch} kv_heads="
          f"{args.kv_heads or 12} iters={args.iters} reps={args.reps}")
    base = None
    for name, use_mesh in (("direct", False), ("mesh1", True)):
        step, util = measure(args.seq, args.batch, args.iters, args.reps,
                             args.kv_heads, use_mesh)
        base = base or step
        print(f"{name:8s} {step * 1e3:8.2f} ms/step  MFU {util:.4f}  "
              f"({(step - base) / base * 100:+.2f}% vs direct)",
              flush=True)


if __name__ == "__main__":
    main()
