"""Per-op TPU profile of a training step: capture a jax.profiler trace
around a few scan iterations and print a per-op duration table
attributed to Python source, so MFU work targets measured cost centers.

    python tools/profile_step.py [--model alexnet|transformer]
        [--batch 8192] [--iters 3] [--top 40]

Parsing recipe: events in the trace with ph=="X" under the TPU device
pid are per-op durations; dividing by the iteration count gives
ms/step.  Op names are XLA fusion names; the table groups by the
leading source annotation when present.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_alexnet(batch):
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.vision import alexnet_cifar10_full

    cfg = alexnet_cifar10_full(batchsize=batch)
    cfg.precision = "bfloat16"
    trainer = Trainer(cfg, {"data": {"pixel": (3, 32, 32), "label": ()}},
                      log_fn=lambda s: None)
    params, opt_state = trainer.init(seed=0)
    rng = np.random.default_rng(0)
    batch_d = {"data": {
        "pixel": jax.device_put(
            rng.standard_normal((batch, 3, 32, 32)).astype(np.float32)),
        "label": jax.device_put(
            rng.integers(0, 10, (batch,)).astype(np.int32)),
    }}
    return trainer, params, opt_state, batch_d


def build_transformer(batch, seq_len=1024):
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)

    cfg = transformer_lm(vocab_size=32768, num_layers=12, embed_dim=768,
                         num_heads=12, head_dim=64, seq_len=seq_len,
                         batchsize=batch)
    cfg.precision = "bfloat16"
    trainer = Trainer(cfg, {"data": {"input": (seq_len,),
                                     "target": (seq_len,)}},
                      log_fn=lambda s: None)
    params, opt_state = trainer.init(seed=0)
    batch_d = next(synthetic_token_batches(batch, seq_len, 32768))
    batch_d = jax.tree_util.tree_map(jax.device_put, batch_d)
    return trainer, params, opt_state, batch_d


def capture(trainer, params, opt_state, batch_d, iters, outdir):
    import jax

    from singa_tpu.utils.profiler import hard_sync

    key = jax.random.PRNGKey(0)
    # warm/compile outside the trace
    params, opt_state, _ = trainer.train_steps(
        params, opt_state, batch_d, 0, key, iters)
    hard_sync(params)
    with jax.profiler.trace(outdir):
        params, opt_state, _ = trainer.train_steps(
            params, opt_state, batch_d, iters, key, iters)
        hard_sync(params)


def attribute(trainer, params, opt_state, batch_d, iters):
    """Map HLO op names -> (source_file:line, op_name metadata) from the
    compiled train_steps text, so trace fusion names become readable."""
    import jax

    from singa_tpu.utils.profiler import hlo_attribution

    key = jax.random.PRNGKey(0)
    txt = trainer.train_steps.lower(
        params, opt_state, batch_d, 0, key, iters).compile().as_text()
    return hlo_attribution(txt)


def parse(outdir, iters, top, attr=None):
    from singa_tpu.utils.profiler import parse_trace_ops

    try:
        per_op, total_us = parse_trace_ops(outdir)
    except FileNotFoundError as e:
        raise SystemExit(str(e))
    print(f"# trace {outdir}")
    print(f"# total device time {total_us / 1e3 / iters:.2f} ms/step over "
          f"{iters} iters, {len(per_op)} distinct ops")
    print(f"{'ms/step':>9s}  {'%':>5s}  op")
    for name, us in per_op.most_common(top):
        tag = (attr or {}).get(name.split("(")[0], "")
        print(f"{us / 1e3 / iters:9.3f}  {100 * us / total_us:5.1f}  "
              f"{name[:40]:40s}  {tag[:120]}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="alexnet",
                    choices=["alexnet", "transformer"])
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--outdir", default="/tmp/prof_step")
    ap.add_argument("--seq", type=int, default=1024,
                    help="transformer sequence length")
    args = ap.parse_args()
    if args.model == "alexnet":
        built = build_alexnet(args.batch or 8192)
    else:
        built = build_transformer(args.batch or max(8192 // args.seq, 1),
                                  args.seq)
    trainer, params, opt_state, batch_d = built
    attr = attribute(trainer, params, opt_state, batch_d, args.iters)
    capture(*built, args.iters, args.outdir)
    parse(args.outdir, args.iters, args.top, attr)


if __name__ == "__main__":
    main()
