"""Ablation timing for the AlexNet MFU gate: strip one component at a
time from alexnet_cifar10_full and report step-time deltas, so MFU work
targets the real cost centers instead of guesses.  Run on the chip:

    python tools/ablate.py [--batch 8192]
"""

from __future__ import annotations

import argparse
import copy
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def strip(cfg, names):
    """Remove layers by name, rewiring each consumer to the removed
    layer's first source."""
    cfg = copy.deepcopy(cfg)
    layers = cfg.neuralnet.layer
    redirect = {}
    for l in layers:
        if l.name in names:
            redirect[l.name] = l.srclayers[0]
    kept = [l for l in layers if l.name not in names]
    for l in kept:
        l.srclayers = [redirect.get(s, s) for s in l.srclayers]
    cfg.neuralnet.layer = kept
    return cfg


def measure(cfg, batch_size, iters=10, reps=3, fwd_only=False):
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.utils.profiler import hard_sync

    cfg.precision = "bfloat16"
    trainer = Trainer(cfg, {"data": {"pixel": (3, 32, 32), "label": ()}},
                      log_fn=lambda s: None)
    params, opt_state = trainer.init(seed=0)
    rng = np.random.default_rng(0)
    batch = {"data": {
        "pixel": jax.device_put(
            rng.standard_normal((batch_size, 3, 32, 32)).astype(np.float32)),
        "label": jax.device_put(
            rng.integers(0, 10, (batch_size,)).astype(np.int32)),
    }}
    key = jax.random.PRNGKey(0)
    if fwd_only:
        net = trainer.train_net

        def fwd_scan(p, b, k, n):
            def body(carry, step):
                loss, _, _ = net.apply(p, b, rng=k, train=True,
                                       compute_dtype=trainer.compute_dtype,
                                       step=step)
                return carry + loss.astype(np.float32), None
            tot, _ = jax.lax.scan(body, 0.0, np.arange(n))
            return tot
        run = jax.jit(fwd_scan, static_argnums=(3,))
        run(params, batch, key, iters).block_until_ready()
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            hard_sync(run(params, batch, key, iters))
            best = min(best, (time.perf_counter() - t0) / iters)
        return best
    params, opt_state, _ = trainer.train_steps(
        params, opt_state, batch, 0, key, iters)
    hard_sync(params)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        params, opt_state, _ = trainer.train_steps(
            params, opt_state, batch, iters, key, iters)
        hard_sync(params)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--fwd", action="store_true")
    args = ap.parse_args()

    from singa_tpu.models.vision import alexnet_cifar10_full

    base_cfg = alexnet_cifar10_full(batchsize=args.batch)
    ave_cfg = copy.deepcopy(base_cfg)
    for l in ave_cfg.neuralnet.layer:
        if l.pooling_param:
            l.pooling_param.pool = "AVE"
    variants = {
        "full": base_cfg,
        "pools-ave": ave_cfg,
        "no-lrn": strip(base_cfg, {"norm1", "norm2"}),
        "no-lrn-ave": strip(ave_cfg, {"norm1", "norm2"}),
    }
    base_ms = None
    for name, cfg in variants.items():
        try:
            s = measure(copy.deepcopy(cfg), args.batch, fwd_only=args.fwd)
        except Exception as e:
            print(f"{name:12s} FAILED: {e!r}")
            continue
        ms = s * 1e3
        if name == "full":
            base_ms = ms
        delta = f"  delta {ms - base_ms:+8.2f}ms" if base_ms else ""
        print(f"{name:12s} {ms:8.2f}ms{delta}", flush=True)


if __name__ == "__main__":
    main()
