"""Measure the GPipe pipeline bubble fraction vs the analytic model.

The schedule runs m microbatches over S stages in m + S - 1 ticks, so
the idle ("bubble") fraction of each device is (S-1)/(m+S-1).  This
tool times the forward pipeline on the virtual CPU mesh across m and
compares the measured per-microbatch cost ratio to the model:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/pipeline_bubble.py

Bubble is measured as 1 - t(m_ref)/t(m) * (m/m_ref_ideal...) — more
robustly, per-tick time is estimated from the largest-m run (most
bubble-free), and bubble(m) = 1 - ideal_ticks/actual_ticks where
actual_ticks = t(m)/tick_cost.  The result lands in docs/PARITY.md.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    # force the virtual CPU mesh (the axon plugin pins jax_platforms at
    # interpreter startup; env vars alone cannot override it)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    from jax.extend.backend import clear_backends
    clear_backends()
    import jax.numpy as jnp

    from singa_tpu.parallel.mesh import make_mesh
    from singa_tpu.parallel.pipeline import pipeline_apply

    S = 4
    devs = jax.devices()
    if len(devs) < S:
        raise SystemExit(f"need {S}+ devices "
                         f"(xla_force_host_platform_device_count)")
    mesh = make_mesh(devs[:S], pipe=S)
    d = 256
    w = jnp.stack([jnp.eye(d) * (1 + 0.01 * i) for i in range(S)])

    def stage_fn(params, mb):
        # enough work per tick that schedule overhead doesn't dominate
        h = mb
        for _ in range(4):
            h = jnp.tanh(h @ params)
        return h

    results = {}
    for m in (4, 8, 16, 32, 64):
        x = jnp.ones((m, 16, d), jnp.float32)
        fn = jax.jit(lambda ww, xx: pipeline_apply(
            mesh, stage_fn, ww, xx, axis="pipe"))
        fn(w, x).block_until_ready()
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            fn(w, x).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        results[m] = best
        print(f"m={m:3d}  t={best * 1e3:8.2f} ms  ticks={m + S - 1}",
              flush=True)

    # per-tick cost from consecutive m (the schedule adds exactly
    # (m2 - m1) ticks between runs, bubble-independent)
    ms_sorted = sorted(results)
    ticks = {m: m + S - 1 for m in ms_sorted}
    slopes = [(results[b] - results[a]) / (ticks[b] - ticks[a])
              for a, b in zip(ms_sorted, ms_sorted[1:])]
    tick_cost = float(np.median(slopes))
    print(f"\nper-tick cost (median slope): {tick_cost * 1e3:.3f} ms")
    print(f"{'m':>4s} {'model bubble':>13s} {'measured bubble':>16s}")
    for m in ms_sorted:
        model = (S - 1) / (m + S - 1)
        ideal = m * tick_cost
        measured = 1 - ideal / results[m]
        print(f"{m:4d} {model:13.3f} {measured:16.3f}")

    # ---- circular/interleaved schedule: same S total virtual stages on
    # a P = S/v pipe axis.  Model: ticks = v*m + P - 1 at 1/1 the tick
    # work (the stage slices are the same matrices), so
    # bubble = (P-1)/(v*m+P-1) vs GPipe's (S-1)/(m+S-1) at equal m.
    v = 2
    Pp = S // v
    cmesh = make_mesh(devs[:Pp], pipe=Pp)
    print(f"\ncircular schedule: {S} virtual stages on pipe={Pp} (v={v})")
    cres = {}
    for m in (4, 8, 16, 32, 64):
        x = jnp.ones((m, 16, d), jnp.float32)
        fn = jax.jit(lambda ww, xx: pipeline_apply(
            cmesh, stage_fn, ww, xx, axis="pipe", virtual=v))
        fn(w, x).block_until_ready()
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            fn(w, x).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        cres[m] = best
        print(f"m={m:3d}  t={best * 1e3:8.2f} ms  "
              f"ticks={v * m + Pp - 1}", flush=True)
    # circular tick cost from its OWN slope (the two meshes place
    # different device counts on the host, so GPipe's tick cost does
    # not transfer)
    cms = sorted(cres)
    cticks = {m: v * m + Pp - 1 for m in cms}
    cslopes = [(cres[b] - cres[a]) / (cticks[b] - cticks[a])
               for a, b in zip(cms, cms[1:])]
    ctick = float(np.median(cslopes))
    print(f"per-tick cost (median slope): {ctick * 1e3:.3f} ms")
    print(f"{'m':>4s} {'model bubble':>13s} {'measured bubble':>16s} "
          f"{'gpipe model':>12s}")
    for m in cms:
        ideal = v * m * ctick
        measured = 1 - ideal / cres[m]
        model = (Pp - 1) / (v * m + Pp - 1)
        gpipe = (S - 1) / (m + S - 1)
        print(f"{m:4d} {model:13.3f} {measured:16.3f} {gpipe:12.3f}",
              flush=True)
    print("\nNB: virtual CPU devices share host cores, so an idle "
          "device donates its core to busy ones and measured bubbles "
          "read high/noisy; the tick counts (printed per run) are the "
          "exact schedule lengths, and on real chips the bubble "
          "follows them.")


if __name__ == "__main__":
    main()
