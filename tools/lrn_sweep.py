"""Sweep LRN Pallas kernel geometry on the chip vs the jnp band-dot
path, standalone, on the AlexNet norm1/norm2 shapes.

    python tools/lrn_sweep.py

Measurement rules for the tunneled chip (see bench.py): everything
scan-wrapped in ONE compiled program (per-call dispatch costs seconds
over the tunnel) and synced with hard_sync, never block_until_ready.
Each config times fwd+bwd together in one compile.  The kernels see
the (H*W, C, N) batch-in-lanes view; in-net boundary-layout effects
are measured separately by the full-step A/B.
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ITERS = 10


def time_scan(body, init, reps):
    """ms per body application, scanned ITERS times in one program."""
    import jax

    from singa_tpu.utils.profiler import hard_sync

    def prog(c):
        out, _ = jax.lax.scan(lambda cc, _: (body(cc), None), c, None,
                              length=ITERS)
        return out
    jfn = jax.jit(prog)
    out = jfn(init)
    hard_sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jfn(init)
        hard_sync(out)
        best = min(best, (time.perf_counter() - t0) / ITERS)
    return best * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=4)
    ap.add_argument("--shapes", default="norm1,norm2")
    args = ap.parse_args()
    import jax.numpy as jnp

    from singa_tpu.ops import lrn_pallas as lp
    from singa_tpu.ops.lrn import _lrn_nhwc_bwd, _lrn_nhwc_fwd

    shapes = {"norm1": (8192, 32, 32, 64, 5, 1e-4),
              "norm2": (8192, 16, 16, 192, 5, 1e-4)}
    rng = np.random.default_rng(0)
    for name in args.shapes.split(","):
        n, h, w, c, lsize, alpha = shapes[name]
        x = jnp.asarray(rng.standard_normal((n, h, w, c)), jnp.bfloat16)
        g = jnp.asarray(rng.standard_normal((n, h, w, c)), jnp.bfloat16)
        xt = jnp.asarray(np.ascontiguousarray(np.transpose(np.asarray(
            x, np.float32), (1, 2, 3, 0)).reshape(h * w, c, n)),
            jnp.bfloat16)
        gt = jnp.asarray(np.ascontiguousarray(np.transpose(np.asarray(
            g, np.float32), (1, 2, 3, 0)).reshape(h * w, c, n)),
            jnp.bfloat16)
        band = jnp.asarray(lp._np_band(c, lsize), jnp.bfloat16)

        def jnp_body(carry):
            xx, gg = carry
            y = _lrn_nhwc_fwd(xx, lsize, alpha, 0.75, 1.0, True, "jnp")[0]
            (dx,) = _lrn_nhwc_bwd(lsize, alpha, 0.75, 1.0, True, "jnp",
                                  xx, gg)
            return (dx, y)
        ms = time_scan(jnp_body, (x, g), args.reps)
        print(f"{name} jnp fwd+bwd                  {ms:7.3f} ms",
              flush=True)

        for n_blk, hw_blk, par in [(256, None, False), (256, None, True),
                                   (512, 8, True), (1024, 1, True),
                                   (1024, 4, True), (2048, 1, True)]:
            fkern = functools.partial(
                lp._fwd_kernel, coef=alpha / lsize, knorm=1.0, beta=0.75,
                relu=True)
            bkern = functools.partial(
                lp._bwd_kernel, coef=alpha / lsize, knorm=1.0, beta=0.75,
                relu=True)

            def pl_body(carry, fk=fkern, bk=bkern, nb=n_blk, hb=hw_blk,
                        pr=par):
                xx, gg = carry
                y = lp._call(fk, [xx], band, jnp.bfloat16, h * w, c, n,
                             nb, False, hb, pr)
                dx = lp._call(bk, [xx, gg], band, jnp.bfloat16, h * w,
                              c, n, nb, False, hb, pr)
                return (dx, y)
            try:
                ms = time_scan(pl_body, (xt, gt), args.reps)
            except Exception as e:
                print(f"{name} pallas n{n_blk} hw{hw_blk} p{int(par)} "
                      f"FAILED {type(e).__name__}: {str(e)[:90]}",
                      flush=True)
                continue
            print(f"{name} pallas n{n_blk:5d} hw{str(hw_blk):>4s} "
                  f"par{int(par)}  fwd+bwd {ms:7.3f} ms", flush=True)


if __name__ == "__main__":
    main()
