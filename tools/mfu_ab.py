"""In-process A/B of AlexNet MFU levers on the chip.  The tunneled
chip drifts ~40% over a session, so only same-process comparisons are
trustworthy; this runs each variant's best-of scan windows back to
back and prints deltas vs the first (baseline) variant.

    python tools/mfu_ab.py [--batch 8192] [--iters 10] [--reps 4]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def measure(batch_size, iters, reps, vmem=None, unroll=1):
    import jax

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.vision import alexnet_cifar10_full
    from singa_tpu.utils.profiler import hard_sync
    import time

    os.environ["SINGA_TPU_SCAN_UNROLL"] = str(unroll)
    old = Trainer.TPU_CONV_COMPILER_OPTIONS
    if vmem is not None:
        Trainer.TPU_CONV_COMPILER_OPTIONS = {
            "xla_tpu_scoped_vmem_limit_kib": str(vmem)}
    try:
        cfg = alexnet_cifar10_full(batchsize=batch_size)
        cfg.precision = "bfloat16"
        trainer = Trainer(cfg, {"data": {"pixel": (3, 32, 32),
                                         "label": ()}},
                          log_fn=lambda s: None)
        params, opt_state = trainer.init(seed=0)
        rng = np.random.default_rng(0)
        batch = {"data": {
            "pixel": jax.device_put(rng.standard_normal(
                (batch_size, 3, 32, 32)).astype(np.float32)),
            "label": jax.device_put(rng.integers(
                0, 10, (batch_size,)).astype(np.int32))}}
        key = jax.random.PRNGKey(0)
        params, opt_state, _ = trainer.train_steps(
            params, opt_state, batch, 0, key, iters)
        hard_sync(params)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            params, opt_state, _ = trainer.train_steps(
                params, opt_state, batch, iters, key, iters)
            hard_sync(params)
            best = min(best, (time.perf_counter() - t0) / iters)
        return best * 1e3
    finally:
        Trainer.TPU_CONV_COMPILER_OPTIONS = old
        os.environ.pop("SINGA_TPU_SCAN_UNROLL", None)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--reps", type=int, default=4)
    ap.add_argument("--variants", default="base,vmem112,vmem104,unroll2,"
                                          "batch12288")
    args = ap.parse_args()
    variants = {
        "base": {},
        "vmem112": {"vmem": 114688},
        "vmem104": {"vmem": 106496},
        "vmem90": {"vmem": 92160},
        "unroll2": {"unroll": 2},
        "unroll5": {"unroll": 5},
        "batch12288": {"batch": 12288},
        "batch16384": {"batch": 16384},
    }
    base_ms = None
    for name in args.variants.split(","):
        kw = dict(variants[name])
        b = kw.pop("batch", args.batch)
        try:
            ms = measure(b, args.iters, args.reps, **kw)
        except Exception as e:
            print(f"{name:12s} FAILED {type(e).__name__}: "
                  f"{str(e)[:100]}", flush=True)
            continue
        per_img = ms / b * 8192     # normalize to img-time at batch 8192
        if base_ms is None:
            base_ms = per_img
        print(f"{name:12s} {ms:8.3f} ms/step  ({per_img:8.3f} ms per "
              f"8192 imgs, {per_img - base_ms:+7.3f} vs base)",
              flush=True)


if __name__ == "__main__":
    main()
