#!/usr/bin/env bash
# MNIST launcher — successor of the reference's ssh-loop run.sh.
#
# Single host / single chip:
#   ./run.sh [mlp|conv] [extra flags...]
# Multi-host (one invocation per host, like the reference's -procsID=$i):
#   ./run.sh conv -hostfile hostfile -procsID $i
#
# Falls back to synthetic data automatically when no shard data exists at
# the config's data path; build real shards with
# `python -m singa_tpu.tools.loader create mnist`.
set -e
cd "$(dirname "$0")/../.."
MODEL="${1:-conv}"
shift || true
exec python -m singa_tpu.main -model_conf "examples/mnist/${MODEL}.conf" "$@"
