#!/usr/bin/env bash
# Benchmark sweep — successor of the reference's batch.sh nworkers x
# nservers x nthreads grid.  On TPU the sweep axes are batch size and
# precision; one JSON line per run is appended to sweep.jsonl.
set -e
cd "$(dirname "$0")/../.."
exec bash examples/sweep.sh "$@"
