#!/usr/bin/env bash
# Benchmark sweep — the successor of the reference's examples/mnist/batch.sh
# (nworkers x nservers x nthreads grid): here the grid is batch size x
# precision on the visible accelerator. One JSON line per run is appended
# to sweep.jsonl (primary metric from bench.py stdout; MFU extras on
# stderr go to sweep.log).
set -euo pipefail
cd "$(dirname "$0")/.."
out=${1:-sweep.jsonl}
: > "$out"
for batch in 128 256 512 1024; do
  echo "== batch=$batch ==" >&2
  python - >> "$out" 2>> sweep.log <<EOF
import sys
sys.argv.append("--extra")
import bench
bench.BATCH = $batch
bench.main()
EOF
done
echo "wrote $out"
